"""
FleetModelBuilder: build MANY Machines in one XLA program per bucket.

The reference trains each Machine in its own Argo pod (one container, one
Keras fit — SURVEY.md §3.1). Here the fleet is the unit: Machines are
bucketed by architecture/shape (gordo_tpu.parallel.bucketing), each bucket's
data is stacked and padded onto a common grid, and a single vmapped,
mesh-sharded program trains every model in the bucket simultaneously —
including the cross-validation folds used for anomaly-threshold calibration,
which run as additional fleet fits with per-machine fold masks instead of
per-machine sklearn loops.

Supported model shapes (the reference's flagship configs):

- a bare JAX estimator definition (AutoEncoder / LSTM*),
- sklearn Pipeline(prefix transformers... , JAX estimator) — prefix
  transformers are fitted per machine on host (they are tiny) and applied
  before stacking,
- DiffBasedAnomalyDetector wrapping either of the above.

Anything else falls back to the per-machine ModelBuilder path, so the fleet
builder never rejects a config — it just loses the batching speedup.

Outputs are per-machine (model, Machine) pairs with the same artifact layout
and metadata as ModelBuilder, so serving and clients are oblivious to how
the model was trained.
"""

import json
import logging
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeoutError
from datetime import datetime, timezone
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np
import pandas as pd
from sklearn.base import BaseEstimator, TransformerMixin
from sklearn.model_selection import TimeSeriesSplit
from sklearn.pipeline import Pipeline

from gordo_tpu import __version__, serializer
from gordo_tpu.builder.build_model import ModelBuilder
from gordo_tpu.client.utils import backoff_seconds
from gordo_tpu.data import _get_dataset
from gordo_tpu.machine import Machine
from gordo_tpu.machine.metadata import (
    BuildMetadata,
    CrossValidationMetaData,
    DatasetBuildMetadata,
    ModelBuildMetadata,
)
from gordo_tpu.models.anomaly.diff import DiffBasedAnomalyDetector
from gordo_tpu.models.core import BaseJaxEstimator
from gordo_tpu.observability import (
    emit_event,
    get_registry,
    memory_watermarks,
    tracing,
    write_telemetry_report,
)
from gordo_tpu.parallel.bucketing import (
    BucketPlan,
    get_policy,
    plan_padding_waste,
    timestep_bucket,
)
from gordo_tpu.parallel.fleet import FleetTrainer, StackedData
from gordo_tpu.parallel.mesh import auto_device_mesh
from gordo_tpu.parallel.precision import (
    DEFAULT_PRECISION_TOLERANCE,
    cast_params,
    mae,
    mae_parity,
    resolve_precision,
)
from gordo_tpu.robustness import faults
from gordo_tpu.utils import atomic

logger = logging.getLogger(__name__)

#: Per-build casualty record persisted next to the artifacts; the model
#: server reads it to 409 predictions against failed/quarantined machines
#: (docs/robustness.md).
BUILD_REPORT_FILENAME = "build_report.json"


class MachineFetchError(RuntimeError):
    """One machine's data fetch failed after its retry budget."""

    def __init__(self, machine_name: str, attempts: int, cause: BaseException):
        super().__init__(
            f"Data fetch for machine {machine_name!r} failed after "
            f"{attempts} attempt(s): {cause!r}"
        )
        self.machine_name = machine_name
        self.attempts = attempts
        self.cause = cause


def _find_jax_estimator(model) -> Optional[BaseJaxEstimator]:
    """Terminal JAX estimator inside (possibly nested) model, or None."""
    if isinstance(model, BaseJaxEstimator):
        return model
    if isinstance(model, DiffBasedAnomalyDetector):
        return _find_jax_estimator(model.base_estimator)
    if isinstance(model, Pipeline):
        return _find_jax_estimator(model.steps[-1][1])
    return None


def _prefix_transformers(model) -> List[TransformerMixin]:
    """
    Host-side transformer steps applied before the JAX estimator, in
    application order — recursing the same wrappers _find_jax_estimator
    does, so nested pipelines surface their inner scalers too.
    """
    if isinstance(model, DiffBasedAnomalyDetector):
        return _prefix_transformers(model.base_estimator)
    if isinstance(model, Pipeline):
        outer = [step for _, step in model.steps[:-1]]
        return outer + _prefix_transformers(model.steps[-1][1])
    return []


class FleetModelBuilder:
    """
    Parameters
    ----------
    machines
        The Machines to build (possibly heterogeneous; they are bucketed).
    mesh
        Device mesh to shard fleets over; None = single default device.
    data_threads
        Thread-pool width for the I/O-bound data-fetch phase.
    epoch_chunk
        Default number of epochs fused into one compiled program per
        bucket fit (``FleetTrainer(epoch_chunk=...)``): chunked fits pay
        one host sync per K epochs instead of per epoch — the lever that
        matters on tunneled/DCN-attached backends. A machine config may
        override it per bucket with an ``epoch_chunk`` fit arg on its
        estimator. Scheduling only; results are bit-identical.
    on_error
        Per-machine failure policy (docs/robustness.md). ``"raise"``
        (default, the reference's semantics): the first machine whose
        data fetch or build fails aborts the whole build. ``"skip"``:
        the casualty is recorded — cause and attempt count, in
        ``build_report.json`` and the telemetry report — and the
        surviving machines build on; the machine is the fault domain,
        not the fleet.
    fetch_retries
        Retries per machine for the data-fetch phase (exponential
        backoff between attempts; the fetch that dies three times on a
        flapping source shouldn't cost the build).
    fetch_timeout
        Per-machine cap, in seconds, on waiting for one machine's fetch
        (all attempts included). None = wait forever. A machine that
        times out is a fetch failure under ``on_error``.
    fetch_backoff
        Seconds to sleep before retry ``attempt`` (1-based); defaults to
        the client's shared exponential policy
        (``client.utils.backoff_seconds``).
    initial_params
        Warm-start initialization (docs/lifecycle.md): machine name →
        host param pytree (the served artifact's ``est.params_``). A
        bucket whose machines ALL have an entry trains from those
        params instead of a fresh init — both the CV fold fits and the
        final fit, so refit thresholds are calibrated against the same
        warm trajectory the candidate trains along. A bucket with any
        machine missing (or a tree that no longer matches the model
        spec) falls back to cold init with a warning — warm start is an
        optimization, never a correctness gate.
    fault_sites
        ``GORDO_FAULT_INJECT`` sites whose nan-mode specs may poison
        this build's fits (robustness/faults.py). The default is the
        ordinary ``("train",)``; lifecycle refits pass
        ``("train", "refit")`` so ``refit:nan:<machine>`` targets refit
        builds without touching unrelated training.
    bucket_policy
        The bucketing-compiler grouping policy (``"exact"`` |
        ``"padded"`` | a ready :class:`~gordo_tpu.parallel.bucketing.
        BucketPolicy`; docs/parallelism.md "Bucketing compiler").
        ``"exact"`` — the default — is the historical one-program-per-
        exact-geometry grouping, pinned bit-identical. ``"padded"``
        coalesces same-architecture-family machines with ragged feature
        widths into one program at power-of-two padded dims; pad
        columns are masked out of loss/metrics/early-stopping during
        training and stripped from predictions at serving.
    precision
        Inference precision mode (``"float32"`` | ``"bf16"`` |
        ``"auto"``; docs/performance.md "Mixed precision"). float32 —
        the default — is the historical path, pinned bit-identical with
        no calibration pass. ``"auto"`` calibrates every machine's bf16
        predictions against its float32 build and serves bf16 only
        where the reconstruction-MAE delta clears
        ``precision_tolerance`` (the per-machine decision lands on
        ``est.precision_`` and in ``build_report.json``). ``"bf16"``
        is the operator override: every machine serves bf16, deltas
        still measured and reported, tolerance breaches logged but not
        enforced. Training is always float32 — precision is an
        inference-time cast of the finished params.
    precision_tolerance
        Relative reconstruction-MAE tolerance for the bf16 calibration
        (default 0.25, the padded-parity bound).
    prefetch_depth
        Host->device transfer pipelining depth (default 0 = off, the
        historical bit-identical path). >0 double-buffers the builder's
        per-bucket stacked-data transfer and the trainer's per-chunk
        transfers (docs/performance.md "transfer pipelining").
    """

    def __init__(
        self,
        machines: List[Machine],
        mesh=None,
        data_threads: int = 8,
        auto_mesh: bool = False,
        epoch_chunk: int = 1,
        on_error: str = "raise",
        fetch_retries: int = 2,
        fetch_timeout: Optional[float] = None,
        fetch_backoff: Callable[[int], float] = backoff_seconds,
        initial_params: Optional[Dict[str, Any]] = None,
        fault_sites: Tuple[str, ...] = ("train",),
        aot_cache: bool = False,
        bucket_policy: Any = "exact",
        precision: str = "float32",
        precision_tolerance: float = DEFAULT_PRECISION_TOLERANCE,
        prefetch_depth: int = 0,
    ):
        self.machines = machines
        if mesh is None and auto_mesh:
            mesh = auto_device_mesh()
        self.mesh = mesh
        self.data_threads = data_threads
        self.epoch_chunk = max(1, int(epoch_chunk))
        if on_error not in ("raise", "skip"):
            raise ValueError(
                f"on_error must be 'raise' or 'skip', got {on_error!r}"
            )
        self.on_error = on_error
        self.fetch_retries = max(0, int(fetch_retries))
        self.fetch_timeout = fetch_timeout
        self.fetch_backoff = fetch_backoff
        self.initial_params = initial_params
        self.fault_sites = tuple(fault_sites)
        #: the bucketing compiler's grouping policy (exact|padded); the
        #: ledger's work plan derives from the same object, so a build's
        #: grouping and its plan fingerprint can never disagree
        self._policy = get_policy(bucket_policy)
        self.bucket_policy = self._policy.name
        #: inference precision mode; stamped onto the policy so every
        #: planned ProgramKey (and through it every ledger unit digest)
        #: carries it — a worker built at one precision can never join
        #: a ledger planned at another
        self.precision = resolve_precision(precision)
        self.precision_tolerance = float(precision_tolerance)
        self._policy.precision = self.precision
        self.prefetch_depth = max(0, int(prefetch_depth))
        #: machine name -> calibration decision of the last build
        #: ({"precision", "mae_delta", "forced"}); empty for float32
        #: builds (no calibration pass runs)
        self.precision_decisions_: Dict[str, dict] = {}
        #: AOT-compile + serialize the built collection's SERVING
        #: programs beside the artifacts (<output>/.programs/), so a
        #: fresh server's cold start is a deserialize instead of a
        #: retrace (docs/performance.md "AOT executable cache"). Off by
        #: default at the API layer (tests build thousands of tiny
        #: fleets); the build-fleet CLI defaults it ON.
        self.aot_cache = bool(aot_cache)
        #: the last build's bucket plan (set by _build_all; the
        #: benchmark and tests read program counts from it)
        self.plan_: Optional[List[BucketPlan]] = None
        #: per-bucket telemetry accumulated by _build_bucket, assembled
        #: into telemetry_report_ (and persisted next to artifacts) by
        #: build()
        self._bucket_reports: List[dict] = []
        self.telemetry_report_: Optional[dict] = None
        #: casualty records of the last build: machines whose fetch or
        #: build failed (on_error="skip"), and machines the non-finite
        #: guard quarantined during training
        self.build_failures_: List[dict] = []
        self.quarantined_: List[dict] = []
        self.build_report_: Optional[dict] = None

    # -- data ------------------------------------------------------------
    def _fetch_one(self, machine: Machine):
        faults.inject("fetch", machine.name)
        dataset = _get_dataset(machine.dataset.to_dict())
        start = time.time()
        X, y = dataset.get_data()
        return {
            "machine": machine,
            "dataset": dataset,
            "X": X,
            "y": y if y is not None else X,
            "query_duration": time.time() - start,
        }

    def _fetch_with_retries(self, machine: Machine):
        """One machine's fetch with its own retry/backoff budget; raises
        :class:`MachineFetchError` (cause + attempt count) when spent."""
        attempts = self.fetch_retries + 1
        for attempt in range(1, attempts + 1):
            try:
                return self._fetch_one(machine)
            except Exception as exc:
                if attempt >= attempts:
                    raise MachineFetchError(machine.name, attempt, exc) from exc
                delay = self.fetch_backoff(attempt)
                logger.warning(
                    "Data fetch for machine %s failed (attempt %d of %d): "
                    "%r; retrying in %.1fs",
                    machine.name, attempt, attempts, exc, delay,
                )
                time.sleep(delay)

    def fetch_data(
        self, machines: List[Machine]
    ) -> Tuple[List[dict], List[dict]]:
        """
        Fetch every machine's data concurrently, each machine in its OWN
        fault domain: per-machine futures with retry/backoff
        (``fetch_retries`` / ``fetch_backoff``) and an optional
        per-machine wait cap (``fetch_timeout``).

        Returns ``(fetched, failures)`` — successes in the input order,
        and one record per casualty (machine, stage, error, attempts).
        Under ``on_error="raise"`` the first casualty re-raises its
        ORIGINAL cause (exception types map to pod exit codes,
        cli.ExceptionsReporter) instead of returning; under ``"skip"``
        the survivors come back and the casualties are recorded.
        """
        failures: List[dict] = []
        fetched: List[dict] = []
        pool = ThreadPoolExecutor(max_workers=self.data_threads)
        hung = False
        # per-machine fetch spans attach to the bucket/build span through
        # an explicit parent — pool workers do not inherit the contextvar
        parent_ctx = tracing.current_context()

        def task(machine: Machine, started_at: dict):
            started_at["t"] = time.monotonic()
            with tracing.start_span(
                "build.fetch", parent=parent_ctx, machine=machine.name
            ):
                return self._fetch_with_retries(machine)

        try:
            futures = []
            for machine in machines:
                started_at: dict = {"t": None}
                futures.append(
                    (machine, pool.submit(task, machine, started_at), started_at)
                )
            # last time ANY machine resolved: while queued fetches wait
            # behind running ones, this is how _await_fetch tells a
            # busy pool (keep waiting) from one wedged by hung fetches
            progress = {"t": time.monotonic()}
            for machine, future, started_at in futures:
                try:
                    fetched.append(
                        self._await_fetch(future, started_at, progress)
                    )
                except FutureTimeoutError:
                    hung = True  # the worker thread cannot be interrupted
                    future.cancel()
                    if self.on_error == "raise":
                        raise TimeoutError(
                            f"Data fetch for machine {machine.name!r} "
                            f"exceeded {self.fetch_timeout}s"
                        )
                    failures.append(self._record_failure(
                        machine.name,
                        phase="fetch",
                        error=f"TimeoutError: fetch exceeded "
                        f"{self.fetch_timeout}s",
                        attempts=None,
                    ))
                except MachineFetchError as exc:
                    if self.on_error == "raise":
                        raise exc.cause
                    failures.append(self._record_failure(
                        machine.name,
                        phase="fetch",
                        error=repr(exc.cause),
                        attempts=exc.attempts,
                    ))
                finally:
                    progress["t"] = time.monotonic()
            return fetched, failures
        finally:
            # wait=False + cancel: a hung fetch thread must not wedge the
            # surviving buckets' build at pool teardown
            pool.shutdown(wait=not hung, cancel_futures=True)

    def _await_fetch(self, future, started_at: dict, progress: dict):
        """
        Wait for one machine's fetch, charging ``fetch_timeout`` against
        the time the fetch has actually been RUNNING — a machine queued
        behind other fetches must not be falsely recorded as its own
        timeout while the pool is making progress. When the pool is
        WEDGED (hung fetches hold every worker and nothing has resolved
        for a whole ``fetch_timeout``), queued machines time out too —
        the bound must hold even when the hung feeds outnumber the
        threads.
        """
        if self.fetch_timeout is None:
            return future.result()
        while True:
            start = started_at["t"]
            if start is None:
                # still queued: poll without starting the machine's clock,
                # unless the whole pool has stalled for a full budget
                if time.monotonic() - progress["t"] > self.fetch_timeout:
                    raise FutureTimeoutError()
                try:
                    return future.result(timeout=0.2)
                except FutureTimeoutError:
                    continue
            remaining = start + self.fetch_timeout - time.monotonic()
            if remaining <= 0:
                raise FutureTimeoutError()
            return future.result(timeout=remaining)

    def _record_failure(
        self,
        machine_name: str,
        phase: str,
        error: str,
        attempts: Optional[int],
    ) -> dict:
        """One casualty: log + build_failures_ + event + counter."""
        record = {
            "machine": machine_name,
            "phase": phase,
            "error": error,
            "attempts": attempts,
        }
        self.build_failures_.append(record)
        logger.error(
            "Machine %s failed in %s phase (on_error=skip; recorded): %s",
            machine_name, phase, error,
        )
        emit_event("build_machine_failed", **record)
        get_registry().counter(
            "gordo_build_machines_failed_total",
            "Machines dropped from fleet builds by per-machine failures",
            ("phase",),
        ).inc(phase=phase)
        return record

    # -- build -----------------------------------------------------------
    def build(
        self,
        output_dir_base: Optional[Union[str, Path]] = None,
        resume: bool = False,
    ) -> List[Tuple[BaseEstimator, Machine]]:
        """
        Build every machine; returns per-machine (model, machine) pairs in
        the original order. Artifacts land at
        ``<output_dir_base>/<machine.name>`` when a base dir is given —
        flushed per BUCKET as each completes, not at the end, so a runtime
        crash mid-build (observed live: the tunneled TPU worker died
        UNAVAILABLE three times during round-5 1000-machine builds) loses
        only the in-flight bucket.

        ``resume`` (requires ``output_dir_base``): machines whose artifact
        directory already loads are reused instead of rebuilt, so re-running
        the same build command after a crash completes the fleet at
        bucket-level granularity. The reference's whole-model resume is the
        sha3 build cache (reference gordo/builder/build_model.py:521-578);
        this is the same idea at the fleet's artifact layer, where the
        crash-unit is a bucket rather than a pod.

        Returns (model, machine) pairs for the machines that BUILT, in
        the original order — under ``on_error="skip"`` failed machines
        are absent from the result and recorded in ``build_failures_`` /
        ``build_report.json`` instead (under the default ``"raise"``
        every machine builds or the call raises, so the result covers
        all of them).
        """
        # the whole build is one trace: bucket/fetch/cv/fit/serialize
        # spans hang off this root, and every event emitted on the build
        # thread (build_started/bucket_finished/build_crashed/...) is
        # stamped with its trace id
        with tracing.start_span(
            "build.fleet", n_machines=len(self.machines), resume=bool(resume)
        ):
            return self._build_all(output_dir_base, resume)

    def _build_all(
        self,
        output_dir_base: Optional[Union[str, Path]] = None,
        resume: bool = False,
    ) -> List[Tuple[BaseEstimator, Machine]]:
        if resume and output_dir_base is None:
            raise ValueError("resume=True requires output_dir_base")
        base = Path(output_dir_base) if output_dir_base is not None else None

        build_start = time.time()
        started_iso = str(datetime.now(timezone.utc).astimezone())
        self._bucket_reports = []
        self.telemetry_report_ = None
        self.build_failures_ = []
        self.quarantined_ = []
        self.build_report_ = None
        self.precision_decisions_ = {}
        emit_event(
            "build_started",
            n_machines=len(self.machines),
            output_dir=str(base) if base is not None else None,
            resume=bool(resume),
        )
        self._compile_cache_start_bytes = self._sample_compile_cache()

        results: Dict[str, Tuple[BaseEstimator, Machine]] = {}
        to_build = list(self.machines)
        if resume:
            reused, remaining = self._scan_resumable(to_build, base)
            results.update(reused)
            if results:
                logger.info(
                    "Resume: %d/%d machines already built under %s",
                    len(results), len(to_build), base,
                )
                emit_event(
                    "resume",
                    n_reused=len(results),
                    n_total=len(to_build),
                    output_dir=str(base),
                )
            to_build = remaining

        with tracing.start_span(
            "build.plan", policy=self.bucket_policy, n_machines=len(to_build)
        ):
            plans = self._policy.plan(to_build)
        self._emit_plan_telemetry(plans, n_machines=len(to_build))
        logger.info(
            "Fleet build: %d machines in %d buckets (policy=%s)",
            len(to_build), len(plans), self.bucket_policy,
        )

        try:
            for plan in plans:
                results.update(self._build_bucket_entry(plan.machines, base))
        except BaseException as exc:
            # the crash context the round-5 worker deaths never left
            # behind: what was in flight and how memory looked at death
            emit_event(
                "build_crashed",
                error=repr(exc),
                n_machines_done=len(results),
                n_machines_total=len(self.machines),
                device_memory=memory_watermarks(),
            )
            raise

        n_resumed = len(self.machines) - len(to_build)
        if base is not None and self.aot_cache:
            self._export_aot_programs(base, results)
        self._finish_telemetry(
            base=base,
            build_start=build_start,
            started_iso=started_iso,
            n_built=len(results) - n_resumed,
            n_resumed=n_resumed,
            n_buckets=len(plans),
        )
        return [results[m.name] for m in self.machines if m.name in results]

    def _emit_plan_telemetry(
        self, plans: List[BucketPlan], n_machines: int
    ) -> None:
        """
        Publish the bucketing compiler's plan: one ``bucket_planned``
        event (programs that will compile, machines per program, the
        planned padding-waste fraction across the feature axes) and the
        ``gordo_build_padding_waste_ratio`` gauge. The same numbers back
        the ``gordo-tpu buckets plan`` dry-run, so what an operator
        previews is what a build reports.
        """
        waste = plan_padding_waste(plans)
        self.plan_ = plans
        emit_event(
            "bucket_planned",
            policy=self.bucket_policy,
            n_programs=len(plans),
            n_machines=n_machines,
            machines_per_program=[len(p.machines) for p in plans],
            padding_waste_ratio=round(waste, 6),
        )
        get_registry().gauge(
            "gordo_build_padding_waste_ratio",
            "Planned fraction of padded (inert) feature cells across the "
            "last build's programs (0 = exact geometry)",
        ).set(waste)

    def _scan_resumable(
        self, machines: List[Machine], base: Path
    ) -> Tuple[
        Dict[str, Tuple[BaseEstimator, Machine]], List[Machine]
    ]:
        """
        The resume scan: machines whose artifact under ``base`` already
        loads AND matches their current model/dataset config come back
        as reused (model, machine) pairs; the rest need rebuilding.
        Shared by the whole-fleet resume path and per-unit resume in
        multi-worker builds (``build_unit(resume=True)``).

        A prior run's casualties must NOT resume: a quarantined
        machine's artifact holds frozen last-good params, and reusing
        it while this run rewrites ``build_report.json`` would erase
        the quarantine record and serve those params as healthy.
        Rebuild them instead — a clean rebuild clears the record
        legitimately, a still-faulting one re-records it.
        """
        prior_casualties = self._prior_casualties(base)
        reused: Dict[str, Tuple[BaseEstimator, Machine]] = {}
        remaining: List[Machine] = []
        for machine in machines:
            art_dir = base / machine.name
            if machine.name in prior_casualties:
                logger.info(
                    "Resume: rebuilding %s (recorded as %s by the "
                    "previous run)",
                    machine.name, prior_casualties[machine.name],
                )
                remaining.append(machine)
                continue
            # artifacts flush atomically (serializer.dump renames a
            # complete temp dir into place), so no torn model.pkl /
            # metadata.json split can exist; the explicit file check
            # remains only so load_metadata's parent-directory
            # fallback can't pick up an unrelated metadata.json from
            # OUTPUT_DIR itself
            if not (art_dir / "metadata.json").is_file():
                remaining.append(machine)
                continue
            try:
                model = serializer.load(art_dir)
                stored = serializer.load_metadata(art_dir)
                current = machine.to_dict()
                if (
                    stored.get("model") != current.get("model")
                    or stored.get("dataset") != current.get("dataset")
                ):
                    logger.warning(
                        "Artifact at %s was built from a different "
                        "model/dataset config; rebuilding %s",
                        art_dir, machine.name,
                    )
                    remaining.append(machine)
                    continue
                # graft the current request's user metadata/runtime onto
                # the stored build metadata, like
                # ModelBuilder._restore_cached
                stored["metadata"]["user_defined"] = (
                    machine.metadata.user_defined
                )
                stored["runtime"] = machine.runtime
                restored_machine = Machine.unvalidated(**stored)
            except Exception:  # partial/corrupt artifact: rebuild
                logger.warning(
                    "Artifact at %s exists but does not load; rebuilding %s",
                    art_dir, machine.name,
                )
                remaining.append(machine)
                continue
            reused[machine.name] = (model, restored_machine)
            if self.precision != "float32":
                # a reused artifact's calibration decision rides its
                # pickle (est.precision_); surface it so a --resume
                # build's report still names every machine's precision
                est = _find_jax_estimator(model)
                if est is not None:
                    self.precision_decisions_[machine.name] = {
                        "precision": getattr(
                            est, "precision_", "float32"
                        ),
                        "mae_delta": getattr(
                            est, "precision_mae_delta_", None
                        ),
                        "forced": False,
                        "resumed": True,
                    }
        return reused, remaining

    def _flush_pairs(self, pairs, base: Optional[Path]) -> None:
        """Serialize (model, machine) pairs under ``base`` — one atomic
        artifact directory per machine — and emit the flush event."""
        if base is None:
            return
        pairs = list(pairs)
        for model, machine in pairs:
            with tracing.start_span("build.serialize", machine=machine.name):
                ModelBuilder._save_model(
                    model=model,
                    machine=machine,
                    output_dir=base / machine.name,
                )
        emit_event("bucket_flush", n_models=len(pairs), output_dir=str(base))

    def _build_bucket_entry(
        self, bucket: List[Machine], base: Optional[Path]
    ) -> Dict[str, Tuple[BaseEstimator, Machine]]:
        """
        One bucket end to end: the vmapped fleet path when the bucket
        has a JAX estimator, the per-machine :class:`ModelBuilder`
        fallback otherwise — artifacts flushed as they complete, and
        per-machine casualties recorded under ``on_error="skip"``. Both
        the whole-fleet loop and the multi-worker ledger (one bucket =
        one work unit, builder/ledger.py) build through here.
        """
        results: Dict[str, Tuple[BaseEstimator, Machine]] = {}
        prototype = serializer.from_definition(bucket[0].model)
        if _find_jax_estimator(prototype) is None:
            logger.info(
                "Bucket of %d machine(s) has no JAX estimator; falling "
                "back to per-machine builds",
                len(bucket),
            )
            for machine in bucket:
                try:
                    results[machine.name] = ModelBuilder(machine).build()
                except Exception as exc:
                    if self.on_error == "raise":
                        raise
                    self._record_failure(
                        machine.name, phase="build",
                        error=repr(exc), attempts=None,
                    )
                    continue
                # flush per machine: these unbatched builds are the
                # slowest, so the crash-loss window matters most here
                self._flush_pairs([results[machine.name]], base)
            return results
        try:
            built_bucket = self._build_bucket(bucket)
        except Exception as exc:
            if self.on_error == "raise":
                raise
            # a training-level failure's blast radius is the
            # bucket: record every machine of it not already
            # recorded by the finer-grained fetch/precheck paths
            already = {f["machine"] for f in self.build_failures_}
            for machine in bucket:
                if machine.name not in already:
                    self._record_failure(
                        machine.name, phase="build",
                        error=repr(exc), attempts=None,
                    )
            return results
        results.update(built_bucket)
        self._flush_pairs(built_bucket.values(), base)
        return results

    def build_unit(
        self,
        unit_machines: List[Machine],
        output_dir_base: Union[str, Path],
        resume: bool = False,
    ) -> Tuple[dict, Dict[str, Tuple[BaseEstimator, Machine]]]:
        """
        Build ONE ledger work unit — the machines of a single bucket —
        flushing artifacts under ``output_dir_base`` and returning
        ``(unit_report, built)``: the JSON-serializable record the
        ledger commits (built/resumed/failed/quarantined machine lists
        + bucket telemetry) and the in-memory (model, machine) pairs.

        ``resume`` reuses machines whose artifacts already load — the
        same artifact-level scan the whole-fleet resume path runs, so a
        multi-worker ``--resume`` skips committed units at the LEDGER
        level and already-flushed machines of uncommitted units here.

        Per-unit state is reset on entry, so one builder instance can
        build many units in sequence; the global ``build_report.json``
        is assembled by the ledger's finalize step from the committed
        unit records, not here (builder/ledger.py).
        """
        base = Path(output_dir_base)
        self._bucket_reports = []
        self.build_failures_ = []
        self.quarantined_ = []
        self.precision_decisions_ = {}
        reused: Dict[str, Tuple[BaseEstimator, Machine]] = {}
        to_build = list(unit_machines)
        if resume:
            reused, to_build = self._scan_resumable(to_build, base)
            if reused:
                logger.info(
                    "Resume: %d/%d machines of this unit already built "
                    "under %s",
                    len(reused), len(unit_machines), base,
                )
                emit_event(
                    "resume",
                    n_reused=len(reused),
                    n_total=len(unit_machines),
                    output_dir=str(base),
                )
        built = (
            self._build_bucket_entry(to_build, base) if to_build else {}
        )
        results = {**reused, **built}
        report = {
            "built": sorted(results),
            "resumed": sorted(reused),
            "failed": [dict(r) for r in self.build_failures_],
            "quarantined": [dict(r) for r in self.quarantined_],
            "buckets": [dict(r) for r in self._bucket_reports],
            "precision": {
                name: dict(rec)
                for name, rec in self.precision_decisions_.items()
            },
        }
        return report, results

    def _sample_compile_cache(self) -> Optional[int]:
        """
        Sample the persistent XLA compile cache's on-disk size into the
        ``gordo_compile_cache_dir_bytes`` gauge — called at build start
        AND end; the returned size lets ``_build_all`` stash the start
        value so the persisted telemetry report records the GROWTH (the
        gauge alone is last-write-wins and would only show the end).
        Null-graceful when no cache is enabled (CPU tests,
        ``GORDO_XLA_CACHE_DIR=""``), like the HBM watermark fields.
        """
        from gordo_tpu.utils import compile_cache_dir_bytes

        size = compile_cache_dir_bytes()
        if size is None:
            return None
        get_registry().gauge(
            "gordo_compile_cache_dir_bytes",
            "On-disk bytes of the persistent XLA compile cache",
        ).set(size)
        return size

    def _export_aot_programs(
        self, base: Path, results: Dict[str, Tuple[BaseEstimator, Machine]]
    ) -> None:
        """
        Build-time AOT: compile + serialize the collection's serving
        programs beside the artifacts from the models still in memory.
        Best-effort end to end — the artifacts are already flushed, and
        a failed export only costs the next server its instant cold
        start, never the build.
        """
        from gordo_tpu.programs import export_serving_programs

        try:
            export_serving_programs(
                base,
                models={name: pair[0] for name, pair in results.items()},
            )
        except Exception as exc:  # noqa: BLE001 - export is best-effort
            logger.warning("AOT serving-program export failed: %s", exc)

    def _finish_telemetry(
        self,
        base: Optional[Path],
        build_start: float,
        started_iso: str,
        n_built: int,
        n_resumed: int,
        n_buckets: int,
    ) -> None:
        """Assemble (and persist, when building to disk) the build's
        telemetry report from the per-bucket records."""
        wall = time.time() - build_start
        # rate counts machines BUILT this run: resume-reused artifacts
        # were loaded, not built, and counting them would inflate the
        # north-star models/hour ~(total/rebuilt)x on a mostly-warm resume
        rate = n_built / wall * 3600 if wall > 0 else None
        report = {
            "kind": "fleet_build",
            "started": started_iso,
            "finished": str(datetime.now(timezone.utc).astimezone()),
            "wall_time_s": wall,
            "n_machines": len(self.machines),
            "n_built": n_built,
            "n_resumed": n_resumed,
            "n_buckets": n_buckets,
            "bucket_policy": self.bucket_policy,
            "precision": self.precision,
            "models_per_hour": rate,
            "device_memory": memory_watermarks(),
            "buckets": self._bucket_reports,
            "on_error": self.on_error,
            "machines_failed": list(self.build_failures_),
            "machines_quarantined": list(self.quarantined_),
        }
        self.telemetry_report_ = report
        self.build_report_ = {
            "version": 1,
            "kind": "fleet_build_report",
            "started": started_iso,
            "finished": report["finished"],
            "on_error": self.on_error,
            "n_machines": len(self.machines),
            "n_built": n_built,
            "n_resumed": n_resumed,
            "n_failed": len(self.build_failures_),
            "n_quarantined": len(self.quarantined_),
            "failed": list(self.build_failures_),
            "quarantined": list(self.quarantined_),
            "precision": {
                "mode": self.precision,
                "tolerance": self.precision_tolerance,
                "machines": {
                    name: dict(rec)
                    for name, rec in self.precision_decisions_.items()
                },
            },
        }
        reg = get_registry()
        reg.counter(
            "gordo_build_models_total", "Models produced by fleet builds"
        ).inc(n_built)
        reg.histogram(
            "gordo_build_seconds", "Whole fleet-build wall time"
        ).observe(wall)
        if rate is not None:
            reg.gauge(
                "gordo_build_models_per_hour", "Most recent build's rate"
            ).set(rate)
        peak = report["device_memory"].get("peak_bytes_in_use")
        if peak is not None:
            reg.gauge(
                "gordo_build_peak_hbm_bytes",
                "Peak device memory observed across builds",
            ).set_max(peak)
        end_bytes = self._sample_compile_cache()
        if end_bytes is not None:
            start_bytes = getattr(self, "_compile_cache_start_bytes", None)
            report["compile_cache"] = {
                "start_bytes": start_bytes,
                "end_bytes": end_bytes,
                "grown_bytes": (
                    end_bytes - start_bytes if start_bytes is not None else None
                ),
            }
        if base is not None:
            write_telemetry_report(base, report)
            self._write_build_report(base)
        emit_event(
            "build_finished",
            n_machines=len(self.machines),
            n_resumed=n_resumed,
            n_failed=len(self.build_failures_),
            n_quarantined=len(self.quarantined_),
            wall_time_s=round(wall, 4),
            models_per_hour=rate,
        )

    @staticmethod
    def _prior_casualties(base: Path) -> Dict[str, str]:
        """Machine -> status from an earlier run's ``build_report.json``
        under ``base`` ({} when absent/unreadable)."""
        path = base / BUILD_REPORT_FILENAME
        try:
            with open(path) as fh:
                report = json.load(fh)
        except (OSError, ValueError):
            return {}
        out: Dict[str, str] = {}
        for record in report.get("failed") or []:
            if record.get("machine"):
                out[record["machine"]] = (
                    f"{record.get('phase', 'build')}-failed"
                )
        for record in report.get("quarantined") or []:
            if record.get("machine"):
                out[record["machine"]] = "quarantined"
        return out

    def _write_build_report(self, base: Path) -> Path:
        """
        Persist ``build_report.json`` next to the artifacts — atomically,
        since the model server polls it to decide which machines to 409.
        """
        return atomic.atomic_write_json(
            base / BUILD_REPORT_FILENAME,
            self.build_report_,
            indent=2,
            sort_keys=True,
            default=str,
        )

    def _build_bucket(
        self, bucket: List[Machine]
    ) -> Dict[str, Tuple[BaseEstimator, Machine]]:
        with tracing.start_span("build.bucket", n_machines=len(bucket)):
            return self._build_bucket_traced(bucket)

    def _build_bucket_traced(
        self, bucket: List[Machine]
    ) -> Dict[str, Tuple[BaseEstimator, Machine]]:
        bucket_start = time.time()
        # chaos seam: a `worker:die:fetch` spec kills THIS process here —
        # lease held, nothing published (robustness/faults.py)
        faults.worker_die("fetch")
        fetched, fetch_failures = self.fetch_data(bucket)
        if fetch_failures:
            # on_error="skip" (raise already propagated): the casualties
            # are recorded; the bucket shrinks to the survivors
            bucket = [item["machine"] for item in fetched]
            if not bucket:
                return {}

        # Per-machine host-side prep: build the model object, fit prefix
        # transformers, transform X.
        models = [serializer.from_definition(item["machine"].model) for item in fetched]
        for model, item in zip(models, fetched):
            seed = item["machine"].evaluation.get("seed", 0)
            ModelBuilder._inject_seed(model, seed)
        estimators = [_find_jax_estimator(m) for m in models]
        Xs_t: List[np.ndarray] = []
        ys_np: List[np.ndarray] = []
        for model, item in zip(models, fetched):
            X_t = np.asarray(item["X"], dtype=np.float32)
            for transformer in _prefix_transformers(model):
                X_t = np.asarray(transformer.fit_transform(X_t), dtype=np.float32)
            Xs_t.append(X_t)
            ys_np.append(np.asarray(item["y"], dtype=np.float32))

        # Architecture spec from the first estimator (identical family
        # across the bucket by construction). The program's tensor dims
        # come from the bucketing policy: the exact policy returns the
        # bucket's (uniform) real widths unchanged; the padded policy
        # rounds the post-transform maxima up to power-of-two buckets so
        # ragged-width machines share this one compiled program
        # (docs/parallelism.md "Bucketing compiler").
        in_widths = [X_t.shape[1] for X_t in Xs_t]
        out_widths = [y_np.shape[1] for y_np in ys_np]
        f_prog, f_out_prog = self._policy.program_dims(in_widths, out_widths)
        proto_est = estimators[0]
        proto_est.kwargs.update(
            {"n_features": f_prog, "n_features_out": f_out_prog}
        )
        spec = proto_est._build_spec()
        lookahead = proto_est.lookahead if spec.windowed else 0

        # fail loudly BEFORE training if any machine cannot fill one window
        # (the solo path fails at its predict; masks would otherwise let a
        # short machine "train" on nothing and crash only at serve time) —
        # under on_error="skip" only THAT machine leaves the bucket
        if spec.windowed:
            min_rows = spec.lookback_window + lookahead
            short = [i for i, X_t in enumerate(Xs_t) if len(X_t) < min_rows]
            if short:
                message = (
                    "{name}: {rows} rows after transforms; this windowed "
                    f"model needs at least {min_rows} (lookback "
                    f"{spec.lookback_window} + lookahead {lookahead})"
                )
                if self.on_error == "raise":
                    from gordo_tpu.data.base import InsufficientDataError

                    item, X_t = fetched[short[0]], Xs_t[short[0]]
                    raise InsufficientDataError(
                        "Machine "
                        + message.format(
                            name=item["machine"].name, rows=len(X_t)
                        )
                    )
                for i in short:
                    self._record_failure(
                        fetched[i]["machine"].name,
                        phase="build",
                        error="InsufficientDataError: " + message.format(
                            name=fetched[i]["machine"].name,
                            rows=len(Xs_t[i]),
                        ),
                        attempts=None,
                    )
                keep = [i for i in range(len(fetched)) if i not in set(short)]
                fetched = [fetched[i] for i in keep]
                models = [models[i] for i in keep]
                estimators = [estimators[i] for i in keep]
                Xs_t = [Xs_t[i] for i in keep]
                ys_np = [ys_np[i] for i in keep]
                bucket = [item["machine"] for item in fetched]
                if not bucket:
                    return {}

        # row-count preservation per machine, on its own data: the license
        # for sharing one model_offset probe across the bucket (below)
        rows_preserved = all(
            len(X_t) == len(item["X"]) for item, X_t in zip(fetched, Xs_t)
        )

        # Stack to a common power-of-two grid (so ragged buckets share one
        # compiled program geometry), pad fleet to mesh multiple. Feature
        # axes pad to the program dims; ragged output widths produce the
        # feature_out_weight mask that keeps pad columns out of
        # loss/metrics/early-stopping (parallel/fleet.py).
        n_grid = timestep_bucket(max(len(x) for x in Xs_t))
        m_padded = FleetTrainer.pad_fleet_size(len(bucket), self.mesh)
        Xs_grid = Xs_t
        ys_grid = ys_np
        data = StackedData.from_ragged(
            Xs_grid,
            ys_grid,
            n_machines_padded=m_padded,
            n_timesteps=n_grid,
            n_features=f_prog,
            n_features_out=f_out_prog,
            prefetch_depth=self.prefetch_depth,
        )

        # one compiled fleet program per bucket geometry from here on —
        # the count the padded policy exists to shrink
        get_registry().counter(
            "gordo_build_programs_compiled_total",
            "Compiled fleet programs (one per bucket geometry) built by "
            "fleet builds",
            ("kind",),
        ).inc(kind=self.bucket_policy)
        fit_args = proto_est.extract_supported_fit_args(proto_est.kwargs)
        epochs = int(fit_args.get("epochs", 1))
        batch_size = int(fit_args.get("batch_size", 32))
        es_kwargs = self._early_stopping_kwargs(fit_args)
        # machine-level epoch_chunk (uniform per bucket: buckets are keyed
        # by the model definition) wins over the builder-wide default —
        # including a config's explicit 0/1 ("this bucket trains
        # per-epoch"), which `or` would silently discard
        config_chunk = fit_args.get("epoch_chunk")
        epoch_chunk = max(
            1,
            int(self.epoch_chunk if config_chunk is None else config_chunk),
        )

        trainer = FleetTrainer(
            spec,
            lookahead=lookahead,
            mesh=self.mesh,
            epoch_chunk=epoch_chunk,
            fault_sites=self.fault_sites,
            prefetch_depth=self.prefetch_depth,
        )
        # Per-machine PRNG keys are the SOLO path's init key for the
        # machine's evaluation seed (models/core.py: solo_init_key) —
        # independent of fleet composition, and giving the same machine
        # identical init params whichever builder trains it (quality
        # parity between the two paths is a product promise).
        from gordo_tpu.models.core import solo_init_key

        keys = np.stack(
            [
                np.asarray(
                    solo_init_key(item["machine"].evaluation.get("seed", 0))
                )
                for item in fetched
            ]
            + [np.asarray(solo_init_key(0))] * (m_padded - len(bucket))
        )

        machine_names = [item["machine"].name for item in fetched]
        warm_params = self._stack_warm_params(machine_names, int(m_padded))

        # -- CV folds as masks: threshold calibration + scores ------------
        start_cv = time.time()
        with tracing.start_span("build.cv", n_machines=len(bucket)):
            fold_records = self._run_cv_folds(
                trainer, data, keys, bucket, Xs_grid, ys_grid, models,
                epochs=epochs, batch_size=batch_size, es_kwargs=es_kwargs,
                machine_names=machine_names, warm_params=warm_params,
            )
        cv_duration = time.time() - start_cv

        # -- final full fit ----------------------------------------------
        # chaos seam: `worker:die:train` dies mid-train — CV done, final
        # fit unstarted, no artifacts flushed
        faults.worker_die("train")
        start_fit = time.time()
        with tracing.start_span(
            "build.fit", n_machines=len(bucket), epochs=epochs
        ):
            params, losses = trainer.fit(
                data, keys, epochs=epochs, batch_size=batch_size,
                machine_names=machine_names, params=warm_params, **es_kwargs
            )
        fit_duration = time.time() - start_fit

        # -- quarantine bookkeeping: the FINAL fit's verdict is what the
        # persisted params reflect (a quarantined machine's artifact
        # holds its last finite epoch's params — build_report.json names
        # it so serving can degrade instead of returning garbage)
        n_bucket_quarantined = 0
        healthy = getattr(trainer, "healthy_", None)
        if healthy is not None and not healthy[: len(fetched)].all():
            q_epochs = trainer.quarantine_epoch_
            for i in np.flatnonzero(~healthy[: len(fetched)]):
                name = fetched[i]["machine"].name
                n_bucket_quarantined += 1
                self.quarantined_.append(
                    {"machine": name, "epoch": int(q_epochs[i])}
                )
                logger.warning(
                    "Machine %s was quarantined at epoch %d; its artifact "
                    "holds the last finite params and serving will 409 it",
                    name, int(q_epochs[i]),
                )

        # -- bf16 calibration (precision != float32) ----------------------
        # measure each machine's reconstruction-MAE delta between the
        # float32 program and a bf16 cast of the SAME params/data — the
        # parity statistic the padded policy is judged by — and decide
        # per machine whether it may serve bf16. The float32 default
        # skips this entirely (no calibration pass, bit-identical build).
        precision_records: Dict[str, dict] = {}
        if self.precision != "float32":
            with tracing.start_span(
                "build.calibrate",
                n_machines=len(fetched),
                mode=self.precision,
            ):
                precision_records = self._calibrate_precision(
                    trainer, params, data,
                    machine_names=machine_names,
                    estimators=estimators,
                    Xs_grid=Xs_grid,
                    ys_grid=ys_grid,
                    out_widths=out_widths,
                    spec=spec,
                    lookahead=lookahead,
                )

        # -- unstack into per-machine models + metadata -------------------
        # one bulk device->host transfer for the whole bucket's params
        host_params = trainer.unstack_all(params, len(fetched))
        bucket_offset: Optional[int] = None
        out: Dict[str, Tuple[BaseEstimator, Machine]] = {}
        for i, (model, est, item) in enumerate(zip(models, estimators, fetched)):
            machine: Machine = item["machine"]
            est.spec_ = spec
            est.params_ = host_params[i]
            # the PROGRAM dims are the model's true tensor widths (its
            # module was built with them); a padded machine additionally
            # records its real (active) widths so predict/serving pad
            # inputs and strip pad columns from responses
            # (docs/serving.md "Padded programs")
            est.n_features_ = f_prog
            est.n_features_out_ = f_out_prog
            if in_widths[i] != f_prog or out_widths[i] != f_out_prog:
                est.n_active_features_ = in_widths[i]
                est.n_active_features_out_ = out_widths[i]
            val_series = getattr(trainer, "val_losses_", None)
            # a NaN column marks a machine too small for any validation
            # samples — it has no val_loss history, like the solo path
            # with n_val == 0
            machine_val = (
                val_series[:, i]
                if val_series is not None and not np.isnan(val_series[:, i]).any()
                else None
            )
            est.history_ = {
                "loss": [float(l[i]) for l in losses],
                "params": {
                    "epochs": epochs,
                    "batch_size": batch_size,
                    "samples": int(len(Xs_grid[i])),
                    "metrics": ["loss"]
                    + (["val_loss"] if machine_val is not None else []),
                    "fleet_size": len(bucket),
                },
            }
            if machine_val is not None:
                est.history_["val_loss"] = [float(x) for x in machine_val]
            if isinstance(model, DiffBasedAnomalyDetector):
                model.scaler.fit(item["y"])
                self._apply_thresholds(model, fold_records, i)

            # model_offset = rows the prediction is shorter than the input:
            # pure window arithmetic (lookback/lookahead) for this bucket's
            # single architecture — so probe it once per bucket instead of
            # paying a full predict per machine (one device roundtrip each
            # on tunneled links). Sharing is only sound while no prefix
            # transformer changes row counts (a data-dependent dropper
            # would make the offset machine-specific); `rows_preserved`
            # checks exactly that on every machine's own data, falling
            # back to per-machine probes otherwise.
            if not rows_preserved:
                offset = ModelBuilder._determine_offset(model, item["X"])
            else:
                if bucket_offset is None:
                    bucket_offset = ModelBuilder._determine_offset(model, item["X"])
                offset = bucket_offset
            scores = {
                metric: folds for metric, folds in fold_records["scores"][i].items()
            }
            machine_out = Machine.unvalidated(**machine.to_dict())
            machine_out.metadata.build_metadata = BuildMetadata(
                model=ModelBuildMetadata(
                    model_offset=offset,
                    model_creation_date=str(datetime.now(timezone.utc).astimezone()),
                    model_builder_version=__version__,
                    model_training_duration_sec=fit_duration,
                    cross_validation=CrossValidationMetaData(
                        cv_duration_sec=cv_duration,
                        scores=scores,
                        splits=fold_records["splits"][i],
                    ),
                    model_meta=ModelBuilder._extract_metadata_from_model(model),
                ),
                dataset=DatasetBuildMetadata(
                    query_duration_sec=item["query_duration"],
                    dataset_meta=item["dataset"].get_metadata(),
                ),
            )
            out[machine.name] = (model, machine_out)

        # -- bucket telemetry: rate, final-fit timings, HBM watermark ------
        bucket_wall = time.time() - bucket_start
        bucket_memory = memory_watermarks()
        bucket_report = (
            {
                "n_machines": len(bucket),
                "n_machines_padded": int(m_padded),
                "n_timesteps_grid": int(n_grid),
                "n_features": int(f_prog),
                "n_features_out": int(f_out_prog),
                "bucket_policy": self.bucket_policy,
                # measured (post-transform) feature-axis padding of this
                # program's stack — the build-time counterpart of the
                # plan's estimate
                "padding_waste_ratio": (
                    1.0
                    - (sum(in_widths) + sum(out_widths))
                    / (len(bucket) * (f_prog + f_out_prog))
                ),
                "epochs": epochs,
                "batch_size": batch_size,
                "cv_duration_s": cv_duration,
                "fit_duration_s": fit_duration,
                "bucket_wall_s": bucket_wall,
                "n_machines_quarantined": n_bucket_quarantined,
                # lifecycle refits init from the served revision's params
                # (docs/lifecycle.md); False also covers a refit that FELL
                # BACK to cold init, so the report never overclaims
                "warm_start": warm_params is not None,
                "models_per_hour": (
                    len(bucket) / bucket_wall * 3600 if bucket_wall > 0 else None
                ),
                # the final full fit's telemetry (compile split, steady
                # epoch time, sensor-timesteps/s) — fold fits overwrite
                # this attribute, the final fit runs last
                "fit": getattr(trainer, "fit_telemetry_", None),
                "device_memory": bucket_memory,
                "precision": self.precision,
            }
        )
        if precision_records:
            bucket_report["precision_decisions"] = {
                name: dict(rec) for name, rec in precision_records.items()
            }
        self._bucket_reports.append(bucket_report)
        get_registry().histogram(
            "gordo_build_bucket_seconds",
            "Per-bucket wall time (data fetch + CV + fit + unstack)",
        ).observe(bucket_wall)
        peak = bucket_memory.get("peak_bytes_in_use")
        if peak is not None:
            get_registry().gauge(
                "gordo_build_peak_hbm_bytes",
                "Peak device memory observed across builds",
            ).set_max(peak)
        emit_event(
            "bucket_finished",
            n_machines=len(bucket),
            wall_time_s=round(bucket_wall, 4),
            peak_bytes_in_use=peak,
        )
        return out

    def _calibrate_precision(
        self,
        trainer: FleetTrainer,
        params: Any,
        data: StackedData,
        *,
        machine_names: List[str],
        estimators: List[BaseJaxEstimator],
        Xs_grid: List[np.ndarray],
        ys_grid: List[np.ndarray],
        out_widths: List[int],
        spec: Any,
        lookahead: int,
    ) -> Dict[str, dict]:
        """
        The bf16 calibration pass (docs/performance.md "Mixed
        precision"): predict the whole bucket once at float32 and once
        with params/inputs cast to bfloat16 (exactly the cast serving
        performs), then compare each machine's reconstruction MAE over
        its REAL rows and ACTIVE output columns. A machine whose
        relative MAE delta clears ``precision_tolerance`` may serve
        bf16; one that doesn't stays float32 — under ``--precision
        bf16`` the operator override serves bf16 anyway (breaches
        logged, never silent), while a ``precision:degrade`` chaos spec
        forces the float32 fallback in either mode. Decisions are
        stamped on the estimators (``est.precision_`` — pickled with
        the artifact, so they survive ``--resume`` and ride into
        serving group keys) and recorded for ``build_report.json``.
        """
        import jax.numpy as jnp

        preds32 = np.asarray(
            trainer.predict(params, data.X), dtype=np.float32
        )
        params16 = cast_params(params, jnp.bfloat16)
        X16 = jnp.asarray(data.X).astype(jnp.bfloat16)
        preds16 = np.asarray(
            trainer.predict(params16, X16), dtype=np.float32
        )
        offset = (
            spec.lookback_window - 1 + lookahead if spec.windowed else 0
        )
        records: Dict[str, dict] = {}
        n_bf16 = 0
        worst = 0.0
        hist = get_registry().histogram(
            "gordo_build_precision_mae_delta",
            "Per-machine relative reconstruction-MAE delta of the bf16 "
            "cast vs the float32 build, measured at calibration",
        )
        for i, name in enumerate(machine_names):
            est = estimators[i]
            n_out = max(0, len(Xs_grid[i]) - offset)
            cols = int(out_widths[i])
            y_true = np.asarray(ys_grid[i], dtype=np.float32)[
                offset : offset + n_out, :cols
            ]
            mae32 = mae(preds32[i, :n_out, :cols], y_true)
            mae16 = mae(preds16[i, :n_out, :cols], y_true)
            delta, within = mae_parity(
                mae32, mae16, self.precision_tolerance
            )
            forced = faults.precision_degrade(name)
            if forced:
                decided = "float32"
            elif self.precision == "bf16":
                decided = "bf16"
                if not within:
                    logger.warning(
                        "Machine %s: bf16 MAE delta %.4f exceeds "
                        "tolerance %.4f but --precision bf16 overrides "
                        "the fallback",
                        name, delta, self.precision_tolerance,
                    )
            else:
                decided = "bf16" if within else "float32"
            est.precision_ = decided
            est.precision_mae_delta_ = float(delta)
            records[name] = {
                "precision": decided,
                "mae_delta": float(delta),
                "forced": bool(forced),
            }
            hist.observe(float(delta))
            worst = max(worst, float(delta))
            n_bf16 += decided == "bf16"
        n_fallback = len(machine_names) - n_bf16
        if n_fallback:
            get_registry().counter(
                "gordo_build_precision_fallbacks_total",
                "Machines whose bf16 calibration failed (or was "
                "chaos-forced to fail) and stayed float32",
            ).inc(n_fallback)
        self.precision_decisions_.update(records)
        emit_event(
            "precision_calibrated",
            mode=self.precision,
            tolerance=self.precision_tolerance,
            n_machines=len(machine_names),
            n_bf16=n_bf16,
            n_float32=n_fallback,
            worst_mae_delta=round(worst, 6),
        )
        return records

    def _stack_warm_params(
        self, machine_names: List[str], m_padded: int
    ) -> Optional[Any]:
        """
        The bucket's warm-start init (docs/lifecycle.md): stack
        ``initial_params[name]`` host trees along a leading fleet axis,
        padding with the first machine's tree (padded rows carry zero
        sample weight, so their init is inert). None — cold init — when
        warm start is off, any machine lacks an entry, or the trees no
        longer share one structure (a changed model config).
        """
        if not self.initial_params:
            return None
        trees = [self.initial_params.get(name) for name in machine_names]
        missing = [n for n, t in zip(machine_names, trees) if t is None]
        if missing:
            logger.warning(
                "Warm start: no initial params for %s; bucket falls back "
                "to cold init",
                missing,
            )
            return None
        import jax

        trees = trees + [trees[0]] * (m_padded - len(trees))
        try:
            return jax.tree_util.tree_map(
                lambda *leaves: np.stack(
                    [np.asarray(leaf, dtype=np.float32) for leaf in leaves]
                ),
                *trees,
            )
        except (ValueError, TypeError) as exc:
            logger.warning(
                "Warm start: param trees do not stack (%s); bucket falls "
                "back to cold init",
                exc,
            )
            return None

    @staticmethod
    def _early_stopping_kwargs(fit_args: dict) -> dict:
        """
        Map a bucket's fit configuration onto the fleet trainer's kwargs:
        ``validation_split`` becomes the per-machine holdout (the solo path
        holds out the last fraction of samples whether or not it early-
        stops, models/core.py:264-272 — the fleet must too, or it would
        train on the solo path's validation data), and an EarlyStopping
        callback becomes the per-machine gate, monitoring the validation
        loss exactly when the solo callback would (``val_loss`` monitor
        with a configured split, or its documented fallback to ``loss``).
        Only min-mode loss-family monitors translate; anything else trains
        the full epoch budget (with a warning, so the divergence from the
        single-machine path is visible).
        """
        from gordo_tpu.models.callbacks import EarlyStopping
        from gordo_tpu.models.core import _materialize_callbacks

        out: dict = {}
        vs = float(fit_args.get("validation_split") or 0.0)
        if vs > 0.0:
            out["validation_split"] = vs
        for cb in _materialize_callbacks(fit_args.get("callbacks")):
            if not isinstance(cb, EarlyStopping):
                logger.warning(
                    "Fleet build: callback %s does not translate to the "
                    "fleet path and is ignored there",
                    type(cb).__name__,
                )
                continue
            if "loss" not in cb.monitor or cb.mode == "max":
                logger.warning(
                    "Fleet build: EarlyStopping(monitor=%r, mode=%r) does "
                    "not translate to the fleet path (loss-family metrics "
                    "only); training the full epoch budget",
                    cb.monitor,
                    cb.mode,
                )
                return out
            out.update(
                {
                    "early_stopping_patience": int(cb.patience),
                    "early_stopping_min_delta": abs(float(cb.min_delta)),
                    "early_stopping_start_from_epoch": int(cb.start_from_epoch),
                    # per-machine best-epoch snapshot on device, matching
                    # the single-machine path's Keras semantics
                    "restore_best_weights": bool(cb.restore_best_weights),
                    "early_stopping_on_val": "val" in cb.monitor and vs > 0.0,
                }
            )
            return out
        return out

    def _run_cv_folds(
        self,
        trainer: FleetTrainer,
        data: StackedData,
        keys: np.ndarray,
        bucket: List[Machine],
        Xs_grid: List[np.ndarray],
        ys_grid: List[np.ndarray],
        models: List[BaseEstimator],
        epochs: int,
        batch_size: int,
        n_splits: int = 3,
        es_kwargs: Optional[dict] = None,
        machine_names: Optional[List[str]] = None,
        warm_params: Optional[Any] = None,
    ) -> dict:
        """
        TimeSeriesSplit folds, trained fleet-wide with per-machine train
        masks; returns per-machine thresholds and scores (the reference
        computes these per machine in anomaly/diff.py:134-224).

        ``es_kwargs`` applies the same early stopping to fold fits as the
        final fit — the single-machine path's cross_validate clones also
        run their configured callbacks, and thresholds calibrated from
        fully-trained fold models would be too strict for an early-stopped
        served model.
        """
        from sklearn import metrics as skmetrics

        M, n_grid = data.sample_weight.shape
        splitter = TimeSeriesSplit(n_splits=n_splits)
        spec = trainer.spec
        lb = spec.lookback_window if spec.windowed else 1
        la = trainer.lookahead

        per_machine_folds: List[List[dict]] = [
            list(splitter.split(np.zeros((len(x), 1)))) for x in Xs_grid
        ]

        scores: List[Dict[str, dict]] = [dict() for _ in bucket]
        splits: List[dict] = [dict() for _ in bucket]
        tag_thresholds: List[Optional[pd.Series]] = [None] * len(bucket)
        agg_thresholds: List[Optional[float]] = [None] * len(bucket)
        tag_thr_per_fold: List[dict] = [dict() for _ in bucket]
        agg_thr_per_fold: List[dict] = [dict() for _ in bucket]
        metric_funcs = {
            "explained-variance-score": skmetrics.explained_variance_score,
            "r2-score": skmetrics.r2_score,
            "mean-squared-error": skmetrics.mean_squared_error,
            "mean-absolute-error": skmetrics.mean_absolute_error,
        }
        raw_scores: List[Dict[str, list]] = [
            {m: [] for m in metric_funcs} for _ in bucket
        ]

        for fold in range(n_splits):
            train_mask = np.zeros((M, n_grid), dtype=np.float32)
            for i in range(len(bucket)):
                train_idx, test_idx = per_machine_folds[i][fold]
                train_mask[i, train_idx] = 1.0
                splits[i].update(
                    {
                        f"fold-{fold + 1}-n-train": int(len(train_idx)),
                        f"fold-{fold + 1}-n-test": int(len(test_idx)),
                    }
                )
            fold_params, _ = trainer.fit(
                data,
                keys,
                epochs=epochs,
                batch_size=batch_size,
                extra_weight=train_mask,
                machine_names=machine_names,
                params=warm_params,
                **(es_kwargs or {}),
            )
            preds = trainer.predict(fold_params, data.X)  # (M, n_out, f_out)

            for i, model in enumerate(models):
                _, test_idx = per_machine_folds[i][fold]
                # model output row j corresponds to input row j + lb - 1 + la
                out_offset = lb - 1 + la
                test_out_rows = test_idx - out_offset
                valid = test_out_rows >= 0
                test_out_rows = test_out_rows[valid]
                rows_in = test_idx[valid]
                # predictions carry the PROGRAM's (possibly padded)
                # output width; scores and thresholds are computed on
                # the machine's real columns only (ys_grid is unpadded)
                y_pred = preds[i][test_out_rows][:, : ys_grid[i].shape[1]]
                y_true = ys_grid[i][rows_in]

                for metric_name, func in metric_funcs.items():
                    raw_scores[i][metric_name].append(float(func(y_true, y_pred)))

                if isinstance(model, DiffBasedAnomalyDetector):
                    from sklearn.base import clone as sk_clone

                    # same scaler config as the model, fitted on fold-train
                    # targets only (parity with diff.py: the fold model's
                    # scaler is fitted during the fold fit, pre-test)
                    train_idx_i, _ = per_machine_folds[i][fold]
                    scaler = sk_clone(model.scaler).fit(ys_grid[i][train_idx_i])
                    scaled_true = scaler.transform(y_true)
                    scaled_pred = scaler.transform(y_pred)
                    scaled_mse = pd.Series(
                        ((scaled_pred - scaled_true) ** 2).mean(axis=1)
                    )
                    mae = pd.DataFrame(np.abs(y_pred - y_true))
                    agg_thr = scaled_mse.rolling(6).min().max()
                    tag_thr = mae.rolling(6).min().max()
                    tag_thr.name = f"fold-{fold}"
                    agg_thr_per_fold[i][f"fold-{fold}"] = (
                        float(agg_thr) if np.isfinite(agg_thr) else None
                    )
                    tag_thr_per_fold[i][f"fold-{fold}"] = tag_thr
                    tag_thresholds[i] = tag_thr
                    agg_thresholds[i] = agg_thr

        for i in range(len(bucket)):
            for metric_name, folds in raw_scores[i].items():
                arr = np.asarray(folds)
                entry = {
                    "fold-mean": float(arr.mean()),
                    "fold-std": float(arr.std()),
                    "fold-max": float(arr.max()),
                    "fold-min": float(arr.min()),
                }
                entry.update(
                    {f"fold-{k + 1}": float(v) for k, v in enumerate(folds)}
                )
                scores[i][metric_name] = entry

        return {
            "scores": scores,
            "splits": splits,
            "tag_thresholds": tag_thresholds,
            "agg_thresholds": agg_thresholds,
            "tag_thr_per_fold": tag_thr_per_fold,
            "agg_thr_per_fold": agg_thr_per_fold,
        }

    @staticmethod
    def _apply_thresholds(model: DiffBasedAnomalyDetector, fold_records: dict, i: int):
        # observability parity with the solo cv-fast-path flag: this
        # detector's thresholds came from the bucket's vmapped fold masks
        model.cv_fleet_masks_ = True
        model.feature_thresholds_ = fold_records["tag_thresholds"][i]
        agg = fold_records["agg_thresholds"][i]
        model.aggregate_threshold_ = float(agg) if agg is not None else None
        model.feature_thresholds_per_fold_ = pd.DataFrame(
            {k: v for k, v in fold_records["tag_thr_per_fold"][i].items()}
        ).T
        model.aggregate_thresholds_per_fold_ = fold_records["agg_thr_per_fold"][i]
        model.smooth_aggregate_threshold_ = None
        model.smooth_feature_thresholds_ = None
