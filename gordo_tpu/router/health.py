"""
Per-replica health: the circuit breaker the router routes through.

State machine (docs/serving.md "Sharded serving plane"):

- ``healthy`` — routable. ``eject_after`` CONSECUTIVE failures (passive:
  request outcomes; active: failed ``/healthz`` probes) eject it.
- ``ejected`` — not routable; its shard re-routes to ring successors.
  The ejection window is the house retry policy
  (:func:`gordo_tpu.client.utils.backoff_seconds`, jittered so N routers
  watching one dead replica don't re-probe in lockstep), scaled by
  ``backoff_scale`` and escalating with consecutive ejections.
- ``probation`` — half-open: the window expired (and, when active
  probing is on, a ``/healthz`` probe succeeded), so the replica is
  routable again but on thin ice — the FIRST failure re-ejects with an
  escalated window, the first success closes the breaker back to
  ``healthy`` and emits ``replica_recovered``.

Passive outcomes drive everything; the active prober (router/app.py's
probe loop) only shortens the ejected->probation leg, so the tracker
works identically with probing disabled (tests, single-shot tools).
"""

import threading
import time
import typing

from gordo_tpu.client.utils import DEFAULT_RETRY_JITTER, backoff_seconds
from gordo_tpu.observability import emit_event, get_registry

HEALTHY = "healthy"
EJECTED = "ejected"
PROBATION = "probation"


def _healthy_gauge():
    return get_registry().gauge(
        "gordo_router_replica_healthy",
        "1 while the router considers the replica routable "
        "(healthy/probation), 0 while ejected",
        ("replica",),
    )


class _ReplicaState:
    __slots__ = (
        "state", "consecutive_failures", "ejections", "eject_until",
    )

    def __init__(self):
        self.state = HEALTHY
        self.consecutive_failures = 0
        #: consecutive ejections without an intervening recovery — the
        #: backoff escalation counter, reset on recovery
        self.ejections = 0
        self.eject_until = 0.0


class ReplicaHealthTracker:
    """
    Thread-safe health state for a fixed set of replica ids.

    ``backoff_scale`` maps the house 8/16/32s… schedule onto serving
    failover timescales (scale 0.25 -> 2/4/8s); ``now`` is injectable
    for deterministic tests.
    """

    def __init__(
        self,
        replicas: typing.Iterable[str],
        eject_after: int = 3,
        backoff_scale: float = 0.25,
        lazy_half_open: bool = True,
        now: typing.Callable[[], float] = time.monotonic,
    ):
        self.eject_after = max(1, int(eject_after))
        self.backoff_scale = float(backoff_scale)
        #: with an ACTIVE prober (router/app.py), window expiry alone
        #: must not re-admit a dead replica to live traffic — the probe
        #: owns the ejected->probation leg, so one dead replica costs
        #: probes, not a user-visible casualty per window. Without a
        #: prober (lazy_half_open=True), expiry IS the half-open
        #: mechanism and live traffic takes the probe's role.
        self.lazy_half_open = bool(lazy_half_open)
        self._now = now
        self._lock = threading.Lock()
        self._states: typing.Dict[str, _ReplicaState] = {}
        gauge = _healthy_gauge()
        for replica in replicas:
            self._states[replica] = _ReplicaState()
            gauge.set(1, replica=replica)

    # -- membership --------------------------------------------------------

    def ensure(self, replicas: typing.Iterable[str]) -> None:
        """Track any new replica ids (membership change: adopt). Known
        ids keep their current state — re-adding a live replica must not
        amnesty an open breaker."""
        with self._lock:
            fresh = [r for r in replicas if r not in self._states]
            for replica in fresh:
                self._states[replica] = _ReplicaState()
        for replica in fresh:
            _healthy_gauge().set(1, replica=replica)

    def forget(self, replica: str) -> None:
        """Drop a replica removed from membership (drain): its state and
        its gauge series go away — a decommissioned replica must not
        haunt /healthz snapshots and dashboards as permanently unhealthy.
        In-flight requests still finishing against it no-op harmlessly
        (record_* tolerate unknown ids)."""
        with self._lock:
            self._states.pop(replica, None)
        _healthy_gauge().remove(replica=replica)

    # -- queries -----------------------------------------------------------

    def state(self, replica: str) -> str:
        with self._lock:
            entry = self._states.get(replica)
            if entry is None:
                return EJECTED
            flipped = self._maybe_expire(replica, entry)
            state = entry.state
        if flipped:
            _healthy_gauge().set(1, replica=replica)
        return state

    def routable(self, replica: str) -> bool:
        """Healthy or half-open — the router may send it real traffic."""
        return self.state(replica) != EJECTED

    def probe_due(self, replica: str) -> bool:
        """Ejected AND past its window: the active prober should ask
        ``/healthz`` now (with probing disabled, :meth:`state` flips the
        same replicas straight to probation lazily)."""
        with self._lock:
            entry = self._states.get(replica)
            return (
                entry is not None
                and entry.state == EJECTED
                and self._now() >= entry.eject_until
            )

    def snapshot(self) -> typing.Dict[str, dict]:
        """Per-replica state for /healthz bodies and --status output."""
        out: typing.Dict[str, dict] = {}
        flipped: typing.List[str] = []
        with self._lock:
            for replica, entry in self._states.items():
                if self._maybe_expire(replica, entry):
                    flipped.append(replica)
                out[replica] = {
                    "state": entry.state,
                    "consecutive_failures": entry.consecutive_failures,
                    "ejections": entry.ejections,
                    "retry_in_s": (
                        round(max(0.0, entry.eject_until - self._now()), 3)
                        if entry.state == EJECTED
                        else 0.0
                    ),
                }
        for replica in flipped:
            _healthy_gauge().set(1, replica=replica)
        return out

    def retry_after_s(self, replica: str) -> float:
        """Seconds until the replica's ejection window expires (0 when
        routable) — the Retry-After hint for its shard's casualties."""
        with self._lock:
            entry = self._states.get(replica)
            if entry is None or entry.state != EJECTED:
                return 0.0
            return max(0.0, entry.eject_until - self._now())

    # -- transitions -------------------------------------------------------

    def record_success(self, replica: str, via: str = "request") -> None:
        recovered = False
        with self._lock:
            entry = self._states.get(replica)
            if entry is None:
                return
            self._maybe_expire(replica, entry)
            entry.consecutive_failures = 0
            if entry.state == PROBATION:
                entry.state = HEALTHY
                entry.ejections = 0
                recovered = True
            elif entry.state == EJECTED:
                # a success against an ejected replica (a probe racing
                # the window, or a hedge that landed): close it directly
                entry.state = HEALTHY
                entry.ejections = 0
                recovered = True
        if recovered:
            _healthy_gauge().set(1, replica=replica)
            emit_event("replica_recovered", replica=replica, via=via)

    def record_failure(self, replica: str, via: str = "request") -> bool:
        """One failed call/probe; returns True when this one ejected."""
        ejected_now = False
        backoff = 0.0
        failures = 0
        with self._lock:
            entry = self._states.get(replica)
            if entry is None:
                return False
            self._maybe_expire(replica, entry)
            entry.consecutive_failures += 1
            failures = entry.consecutive_failures
            should_eject = (
                entry.state == PROBATION  # half-open: one strike
                or failures >= self.eject_after
            )
            if should_eject and entry.state != EJECTED:
                entry.state = EJECTED
                entry.ejections += 1
                backoff = (
                    backoff_seconds(
                        entry.ejections, jitter=DEFAULT_RETRY_JITTER
                    )
                    * self.backoff_scale
                )
                entry.eject_until = self._now() + backoff
                ejected_now = True
        if ejected_now:
            _healthy_gauge().set(0, replica=replica)
            emit_event(
                "replica_ejected",
                replica=replica,
                via=via,
                consecutive_failures=failures,
                backoff_s=round(backoff, 3),
            )
        return ejected_now

    def note_probe(self, replica: str, ok: bool) -> None:
        """An active /healthz probe outcome. Success moves an expired
        ejection to probation (half-open) rather than straight to
        healthy: real traffic gets the final vote."""
        if not ok:
            self.record_failure(replica, via="probe")
            return
        with self._lock:
            entry = self._states.get(replica)
            if entry is None:
                return
            if entry.state == EJECTED and self._now() >= entry.eject_until:
                entry.state = PROBATION
                entry.consecutive_failures = 0
        # probation is routable: reflect it on the gauge (recovery event
        # waits for the first real-traffic success)
        if self.state(replica) == PROBATION:
            _healthy_gauge().set(1, replica=replica)

    # -- internals ---------------------------------------------------------

    def _maybe_expire(self, replica: str, entry: _ReplicaState) -> bool:
        """Lazy ejected->probation flip once the window passed (caller
        holds the lock; returns True on flip so the caller can refresh
        the gauge outside it). Disabled under active probing — the probe
        owns this transition there; without one it IS the half-open
        mechanism."""
        if (
            self.lazy_half_open
            and entry.state == EJECTED
            and self._now() >= entry.eject_until
        ):
            entry.state = PROBATION
            entry.consecutive_failures = 0
            return True
        return False
