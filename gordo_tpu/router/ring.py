"""
Consistent hashing of machine names onto replica ids.

Why a ring and not ``hash(name) % N``: membership changes. When a
replica is added or removed, modulo hashing reassigns ~all machines —
every replica's preloaded param stacks and AOT-warmed programs
(docs/performance.md) are invalidated at once. On the ring, a one-replica
change moves only ~1/N of the machines (pinned by
tests/test_router.py's stability property test), so N-1 replicas keep
serving exactly what they already have resident.

Determinism: points come from md5 (stable across processes, platforms
and PYTHONHASHSEED), so a router and every replica — given the same
``(replicas, vnodes)`` shard manifest — independently compute the SAME
owner for every machine. There is no shard-assignment state to
distribute; the manifest IS the shard map.
"""

import bisect
import hashlib
import typing

#: virtual nodes per replica: enough that machine counts per replica
#: concentrate near fair share (spread shrinks ~1/sqrt(vnodes)) while a
#: whole ring for tens of replicas still builds in microseconds
DEFAULT_VNODES = 64


def _hash64(value: str) -> int:
    """First 8 bytes of md5 as an int — the ring's point space."""
    return int.from_bytes(
        hashlib.md5(value.encode("utf-8")).digest()[:8], "big"
    )


class HashRing:
    """
    An immutable consistent-hash ring over replica ids.

    Each replica owns ``vnodes`` points at ``md5("<replica>#<i>")``; a
    machine name hashes to a point and is owned by the first replica
    point at or after it (wrapping). Immutability is deliberate:
    membership changes swap in a NEW ring (router/app.py holds the
    reference), so an in-flight fanout keeps routing against the ring it
    started with — drain/adopt without dropping requests.

    >>> ring = HashRing(["r0", "r1", "r2"])
    >>> ring.owner("some-machine") in {"r0", "r1", "r2"}
    True
    >>> ring.owner("some-machine") == HashRing(["r2", "r1", "r0"]).owner(
    ...     "some-machine")  # membership order is irrelevant
    True
    """

    def __init__(
        self,
        replicas: typing.Sequence[str],
        vnodes: int = DEFAULT_VNODES,
    ):
        if not replicas:
            raise ValueError("HashRing needs at least one replica id")
        if len(set(replicas)) != len(replicas):
            raise ValueError(f"Duplicate replica ids: {sorted(replicas)}")
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.replicas: typing.Tuple[str, ...] = tuple(sorted(replicas))
        self.vnodes = int(vnodes)
        points: typing.List[typing.Tuple[int, str]] = []
        for replica in self.replicas:
            for i in range(self.vnodes):
                points.append((_hash64(f"{replica}#{i}"), replica))
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [r for _, r in points]

    def owner(self, machine_name: str) -> str:
        """The replica owning ``machine_name``."""
        index = bisect.bisect_right(self._points, _hash64(machine_name))
        return self._owners[index % len(self._owners)]

    def preference(self, machine_name: str) -> typing.List[str]:
        """
        Every replica in ring order from the machine's point: element 0
        is the owner, the rest are its failover successors — the order
        an ejected owner's shard re-routes in (docs/serving.md).
        """
        start = bisect.bisect_right(self._points, _hash64(machine_name))
        seen: typing.Set[str] = set()
        ordered: typing.List[str] = []
        n = len(self._owners)
        for step in range(n):
            replica = self._owners[(start + step) % n]
            if replica not in seen:
                seen.add(replica)
                ordered.append(replica)
                if len(ordered) == len(self.replicas):
                    break
        return ordered

    def shard(
        self, machine_names: typing.Iterable[str], replica: str
    ) -> typing.Set[str]:
        """The subset of ``machine_names`` owned by ``replica``."""
        return {m for m in machine_names if self.owner(m) == replica}

    def partition(
        self, machine_names: typing.Iterable[str]
    ) -> typing.Dict[str, typing.List[str]]:
        """owner replica -> sorted machines, only non-empty shards."""
        shards: typing.Dict[str, typing.List[str]] = {}
        for name in machine_names:
            shards.setdefault(self.owner(name), []).append(name)
        return {r: sorted(ms) for r, ms in shards.items()}
