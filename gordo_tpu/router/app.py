"""
The fault-tolerant routing tier (docs/serving.md "Sharded serving
plane"): a WSGI app that presents the SAME surface as one ``run-server``
process while the collection's machines actually live sharded across N
replicas.

Per request:

- single-machine routes proxy to the machine's ring owner, failing over
  to ring successors (with the adopt header, server/catalog.py) when the
  owner is ejected;
- fleet routes partition the posted machines by owner, fan the sub-
  requests out concurrently, and re-join the per-machine frames into one
  response — with bounded hedged retries for straggling shards;
- replica health is a per-replica circuit breaker (router/health.py)
  fed by passive request outcomes and the replicas' own ``/healthz``
  probes; a dead replica costs only its shard, only until failover.

Failure is structured all the way down (docs/robustness.md): build
casualties 409 exactly as they would from a single server (the router
reads the same ``build_report.json``); machines whose every candidate
replica is ejected come back as a 409 whose body is marked
``transient`` — the client's :class:`gordo_tpu.client.io.ReplicaUnavailable`
— naming each casualty; melting replicas' 503 + Retry-After propagates
through, and the router sheds at its own door past ``--max-inflight``.
"""

import json
import logging
import os
import threading
import time
import timeit
import traceback
import typing
import uuid
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout
from concurrent.futures import wait as futures_wait

import requests
from werkzeug.exceptions import HTTPException
from werkzeug.routing import Map, Rule
from werkzeug.wrappers import Request, Response

from gordo_tpu import __version__
from gordo_tpu.observability import attribution, emit_event, get_registry, tracing
from gordo_tpu.observability import rollup as rollup_mod
from gordo_tpu.robustness import faults
from gordo_tpu.router.health import ReplicaHealthTracker
from gordo_tpu.router.ring import DEFAULT_VNODES, HashRing
from gordo_tpu.server.app import GordoApp, adapt_proxy_deployment
from gordo_tpu.server.catalog import (
    ADOPT_HEADER,
    ServingCatalog,
    resolve_sibling_revision,
)
from gordo_tpu.server.utils import ApiError

logger = logging.getLogger(__name__)


class RouterConfig:
    """Default router config (mirrors server/app.py's Config idiom)."""

    MODEL_COLLECTION_DIR_ENV_VAR = "MODEL_COLLECTION_DIR"
    #: replica id -> base URL (e.g. {"r0": "http://10.0.0.4:5555"})
    REPLICAS: typing.Dict[str, str] = {}
    VNODES = DEFAULT_VNODES
    #: consecutive failures before a replica is ejected
    EJECT_AFTER = 3
    #: scale on the house 8/16/32s backoff schedule for ejection windows
    BACKOFF_SCALE = 0.25
    #: active /healthz probing of ejected replicas; 0 disables the
    #: prober thread (half-open then happens lazily on window expiry)
    PROBE_INTERVAL_S = 1.0
    #: straggler hedging: a shard call silent for this long gets one
    #: hedge to the next routable successor; 0 disables (default — turn
    #: it on where tail latency matters more than duplicate work)
    HEDGE_MS = 0.0
    #: per-call (connect, read) timeout against replicas
    REPLICA_TIMEOUT_S = 30.0
    #: admission control: concurrent requests in flight past this shed
    #: with 503 + Retry-After at the router's own door
    MAX_INFLIGHT = 64
    #: plane rollup (docs/observability.md "Plane rollup and control
    #: signals"): poll interval for merging member /telemetry/snapshot
    #: registries into the router's /status + /metrics view. 0 disables
    #: the poller thread entirely (the house strict no-op); /status
    #: then polls on demand, per request.
    ROLLUP_INTERVAL_S = 0.0
    #: merged snapshots kept in the persisted JSONL (oldest trimmed)
    ROLLUP_RETENTION = 500
    #: JSONL path periodic merged snapshots persist to (next to the
    #: artifacts, so `gordo-tpu tune` ingests them as observations);
    #: None disables persistence
    ROLLUP_PERSIST_PATH: typing.Optional[str] = None
    #: test seam: a pre-built requests.Session (the loopback harness
    #: injects one routing straight into in-process replica apps)
    SESSION: typing.Optional[typing.Any] = None

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in dir(self) if k.isupper()}


def _json_response(payload: dict, status: int = 200) -> Response:
    return Response(
        json.dumps(payload, default=str),
        status=status,
        mimetype="application/json",
    )


class _RequestCtx:
    def __init__(self):
        self.start_time = timeit.default_timer()
        self.collection_dir = ""
        self.current_revision = ""
        self.revision = ""
        #: the revision the CALLER pinned (param or header), or "" —
        #: must ride every forwarded replica call, or a header-pinned
        #: request would be served from `latest` while stamped with the
        #: pinned name
        self.requested_revision = ""
        self.trace_id = ""
        #: the router-plane phase ledger: downstream replica wait is
        #: "queue", response re-stamping is "serialize"
        self.ledger = attribution.ledger_for("router")

    def forward_params(self, request: Request) -> dict:
        """Query params for a replica call, with the pinned revision
        injected when it arrived as a header rather than a param."""
        params = request.args.to_dict()
        if self.requested_revision and "revision" not in params:
            params["revision"] = self.requested_revision
        return params


class _StreamProxy:
    """One router-held stream session: the client sees ONE session id;
    behind it live per-replica sub-sessions covering the machines each
    replica's shard (or failover successor) owns. ``stale`` marks it
    for the resume contract — set on replica failure mid-update and on
    every membership change (drain: the next update answers the
    structured resume 409 and the client re-establishes on the current
    ring)."""

    __slots__ = (
        "sid", "machines", "subs", "stale", "last_active",
        "project", "params",
    )

    def __init__(
        self,
        sid: str,
        machines: typing.List[str],
        subs: list,
        project: str = "",
        params=None,
    ):
        self.sid = sid
        self.machines = machines
        #: [{"rid", "url", "sid", "machines"}]
        self.subs = subs
        self.stale = False
        self.last_active = time.monotonic()
        #: the project + forwarded params this proxy was OPENED under —
        #: hygiene purges close its sub-sessions with these, not with
        #: whatever project/revision the purging request happens to
        #: carry (a mismatch would refuse at the replica and leak the
        #: device-resident windows the purge exists to free)
        self.project = project
        self.params = params


#: bounds on the router's held-stream table: a publisher that crashes
#: without closing leaves a proxy nobody will ever update, so opens
#: opportunistically purge proxies idle past the window, and the table
#: never grows past the count bound (oldest evicted — safe: an evicted
#: session's next update answers the resume contract). The replicas'
#: own session tables are bounded separately (GORDO_STREAM_MAX_SESSIONS).
STREAM_PROXY_BOUND = 4096
STREAM_PROXY_IDLE_S = 900.0


class _ShardResult:
    """One shard call's terminal outcome."""

    __slots__ = ("kind", "replica", "payload", "status", "retry_after")

    def __init__(self, kind, replica, payload=None, status=None, retry_after=None):
        self.kind = kind  # ok | unavailable | overloaded | refused | error
        self.replica = replica
        self.payload = payload
        self.status = status
        self.retry_after = retry_after


class RouterApp:
    """WSGI router fronting N ``run-server`` shard replicas."""

    _TRACE_EXEMPT_PATHS = frozenset(
        {"/healthcheck", "/healthz", "/metrics", "/status",
         "/telemetry/snapshot"}
    )

    def __init__(self, config: typing.Optional[dict] = None):
        self.config = RouterConfig().to_dict()
        if config:
            self.config.update(config)
        replicas = dict(self.config.get("REPLICAS") or {})
        if not replicas:
            raise ValueError(
                "RouterApp needs at least one replica (REPLICAS config / "
                "run-router --replica id=url)"
            )
        self.vnodes = int(self.config.get("VNODES") or DEFAULT_VNODES)
        self._membership_lock = threading.Lock()
        self._replicas = replicas
        self._ring = HashRing(sorted(replicas), self.vnodes)
        probe_interval = float(self.config.get("PROBE_INTERVAL_S") or 0.0)
        self.health = ReplicaHealthTracker(
            sorted(replicas),
            eject_after=int(self.config.get("EJECT_AFTER") or 3),
            backoff_scale=float(self.config.get("BACKOFF_SCALE") or 0.25),
            # with a prober, the PROBE re-admits a dead replica — live
            # traffic never pays a casualty per expired window
            lazy_half_open=probe_interval <= 0,
        )
        # the same catalog layer the replicas use, for the same
        # artifacts: build-report casualties (409 source of truth) and
        # the collection's machine list. No shard, no batching, no AOT.
        self.catalog = ServingCatalog(aot_cache=False)
        self.hedge_s = float(self.config.get("HEDGE_MS") or 0.0) / 1000.0
        self.replica_timeout_s = float(
            self.config.get("REPLICA_TIMEOUT_S") or 30.0
        )
        self.max_inflight = int(self.config.get("MAX_INFLIGHT") or 64)
        self._inflight = threading.BoundedSemaphore(self.max_inflight)
        self.session = self.config.get("SESSION") or requests.Session()
        # EMA of fanout wall time: the Retry-After estimate for sheds
        self._ema_lock = threading.Lock()
        self._ema_request_s = 0.25
        self._stopping = threading.Event()
        self._prober: typing.Optional[threading.Thread] = None
        if probe_interval > 0:
            self._prober = threading.Thread(
                target=self._probe_loop,
                args=(probe_interval,),
                name="gordo-router-prober",
                daemon=True,
            )
            self._prober.start()

        # plane rollup (docs/observability.md "Plane rollup and control
        # signals"): with an interval the poller thread keeps the merged
        # view warm; without one NOTHING runs — no thread, no member
        # requests (the strict no-op) — and /status|/metrics poll the
        # members synchronously, per request, via a lazy threadless
        # poller.
        self._started_at = time.time()
        self._rollup_lock = threading.Lock()
        self._rollup: typing.Optional["rollup_mod.RollupPoller"] = None
        rollup_interval = float(self.config.get("ROLLUP_INTERVAL_S") or 0.0)
        if rollup_interval > 0:
            self._rollup = self._build_rollup(rollup_interval)
            self._rollup.start()

        self.url_map = Map(
            [
                Rule("/healthcheck", endpoint="healthcheck", methods=["GET"]),
                Rule("/healthz", endpoint="healthz", methods=["GET"]),
                Rule(
                    "/server-version", endpoint="server_version", methods=["GET"]
                ),
                # the plane rollup surface: this process's own snapshot,
                # plus the merged plane view (docs/observability.md
                # "Plane rollup and control signals")
                Rule(
                    "/telemetry/snapshot",
                    endpoint="telemetry_snapshot",
                    methods=["GET"],
                ),
                Rule("/status", endpoint="status", methods=["GET"]),
                Rule("/metrics", endpoint="metrics", methods=["GET"]),
                Rule("/router/replicas", endpoint="replicas", methods=["GET"]),
                Rule(
                    "/router/replicas",
                    endpoint="set_replicas",
                    methods=["POST"],
                ),
                Rule(
                    "/gordo/v0/<gordo_project>/models",
                    endpoint="models",
                    methods=["GET"],
                ),
                Rule(
                    "/gordo/v0/<gordo_project>/revisions",
                    endpoint="revisions",
                    methods=["GET"],
                ),
                Rule(
                    "/gordo/v0/<gordo_project>/<gordo_name>/metadata",
                    endpoint="metadata",
                    methods=["GET"],
                ),
                Rule(
                    "/gordo/v0/<gordo_project>/<gordo_name>/healthcheck",
                    endpoint="metadata",
                    methods=["GET"],
                ),
                Rule(
                    "/gordo/v0/<gordo_project>/<gordo_name>/download-model",
                    endpoint="proxy_get",
                    methods=["GET"],
                ),
                Rule(
                    "/gordo/v0/<gordo_project>/<gordo_name>/prediction",
                    endpoint="single_prediction",
                    methods=["POST"],
                ),
                Rule(
                    "/gordo/v0/<gordo_project>/<gordo_name>/anomaly/prediction",
                    endpoint="single_prediction",
                    methods=["POST"],
                ),
                Rule(
                    "/gordo/v0/<gordo_project>/prediction/fleet",
                    endpoint="fleet_prediction",
                    methods=["POST"],
                ),
                Rule(
                    "/gordo/v0/<gordo_project>/anomaly/prediction/fleet",
                    endpoint="fleet_prediction",
                    methods=["POST"],
                ),
                # streaming scoring plane (docs/serving.md "Streaming
                # scoring"): the router presents ONE session over N
                # shard replicas' sub-sessions
                Rule(
                    "/gordo/v0/<gordo_project>/stream/open",
                    endpoint="stream_open",
                    methods=["POST"],
                ),
                Rule(
                    "/gordo/v0/<gordo_project>/stream/<stream_id>/update",
                    endpoint="stream_update",
                    methods=["POST"],
                ),
                Rule(
                    "/gordo/v0/<gordo_project>/stream/<stream_id>/close",
                    endpoint="stream_close",
                    methods=["POST"],
                ),
            ],
            strict_slashes=False,
        )
        #: router-held stream sessions (docs/serving.md)
        self._streams: typing.Dict[str, _StreamProxy] = {}
        self._streams_lock = threading.Lock()

    # -- membership (drain/adopt) ------------------------------------------

    def routing_view(self) -> typing.Tuple[typing.Dict[str, str], HashRing]:
        """The (replicas, ring) pair a request routes against — captured
        ONCE at request start, so a concurrent membership change never
        re-partitions an in-flight fanout (drain without drops)."""
        with self._membership_lock:
            return self._replicas, self._ring

    def set_replicas(self, replicas: typing.Dict[str, str]) -> None:
        """Swap the membership: the ring is immutable, so this builds a
        new one and publishes it atomically. Removed replicas drain (new
        requests no longer route to them; in-flight ones finish); added
        replicas adopt their ring share on the next request."""
        if not replicas:
            raise ValueError("Replica set cannot be empty")
        ring = HashRing(sorted(replicas), self.vnodes)
        # track health BEFORE publishing the ring: a concurrent request
        # capturing the new ring must not see a freshly added (unknown)
        # replica as ejected and spuriously fail its shard over
        self.health.ensure(replicas)
        with self._membership_lock:
            previous = set(self._replicas)
            self._replicas = dict(replicas)
            self._ring = ring
        removed = sorted(previous - set(replicas))
        for rid in removed:
            self.health.forget(rid)
        # drain the stream plane: every held session's machine->replica
        # partition may have moved, so the next update per session
        # answers the resume contract and the client re-establishes
        # against the NEW ring (docs/serving.md "Streaming scoring")
        with self._streams_lock:
            n_streams = 0
            for proxy in self._streams.values():
                if not proxy.stale:
                    proxy.stale = True
                    n_streams += 1
        emit_event(
            "router_membership_changed",
            added=sorted(set(replicas) - previous),
            removed=removed,
            n_replicas=len(replicas),
            n_streams_drained=n_streams,
        )

    def close(self) -> None:
        self._stopping.set()
        if self._prober is not None:
            self._prober.join(timeout=5.0)
            self._prober = None
        with self._rollup_lock:
            rollup, self._rollup = self._rollup, None
        if rollup is not None:
            rollup.stop()

    # -- plane rollup ------------------------------------------------------

    def _build_rollup(self, interval_s: float) -> rollup_mod.RollupPoller:
        def members() -> typing.Dict[str, str]:
            replicas, _ = self.routing_view()
            return dict(replicas)

        def fetch(url: str) -> dict:
            response = self.session.get(
                url.rstrip("/") + "/telemetry/snapshot",
                timeout=self.replica_timeout_s,
            )
            response.raise_for_status()
            return response.json()

        return rollup_mod.RollupPoller(
            members=members,
            interval_s=interval_s,
            fetch=fetch,
            local_members={"__router__": self._self_snapshot},
            persist_path=self.config.get("ROLLUP_PERSIST_PATH") or None,
            retention=int(self.config.get("ROLLUP_RETENTION") or 500),
            name="router-rollup",
        )

    def _rollup_poller(self) -> rollup_mod.RollupPoller:
        """The embedded poller, or — when no interval is configured — a
        threadless one created lazily on the first /status|/metrics
        request (so an unconfigured rollup costs nothing at all)."""
        with self._rollup_lock:
            if self._rollup is None:
                self._rollup = self._build_rollup(0.0)
            return self._rollup

    def _self_snapshot(self) -> dict:
        """This router process's own /telemetry/snapshot payload — also
        the local member the merged plane view includes."""
        replicas, _ = self.routing_view()
        routable = [r for r in replicas if self.health.routable(r)]
        return rollup_mod.snapshot_payload(
            role="router",
            status={
                "status": "ok" if routable else "no_replicas",
                "replicas": self.health.snapshot(),
                "routable": len(routable),
                "max_inflight": self.max_inflight,
            },
            registry=get_registry(),
            started_at=self._started_at,
        )

    def _merged_snapshot(self) -> dict:
        """The latest merged plane snapshot: cached when the poller
        thread runs, polled synchronously otherwise."""
        poller = self._rollup_poller()
        merged = poller.merged()
        if merged is None or poller.interval_s <= 0:
            merged = poller.poll_once()
        return merged

    # -- health probing ----------------------------------------------------

    def _probe_loop(self, interval: float) -> None:
        while not self._stopping.wait(interval):
            self.probe_ejected()

    def probe_ejected(self) -> None:
        """Ask /healthz of every ejected replica whose window expired;
        success moves it to half-open (router/health.py)."""
        replicas, _ = self.routing_view()
        for rid, base_url in replicas.items():
            if not self.health.probe_due(rid):
                continue
            self.health.note_probe(rid, self._probe_replica(rid, base_url))

    def _probe_replica(self, rid: str, base_url: str) -> bool:
        action = faults.replica_fault_action(rid)
        if action is not None and action[0] == "die":
            return False
        try:
            # probes run serially per cycle: a blackholed replica must
            # not hold the full request timeout and stall every OTHER
            # ejected replica's re-adoption behind it — a healthy
            # /healthz answers in milliseconds
            resp = self.session.get(
                f"{base_url}/healthz",
                timeout=min(3.0, self.replica_timeout_s),
            )
        except Exception:
            return False
        # 503 here is the replica saying "alive but melting": it stays
        # ejected/probing until it reports ready
        return 200 <= resp.status_code < 300

    # -- WSGI plumbing -----------------------------------------------------

    def __call__(self, environ, start_response):
        adapt_proxy_deployment(environ)
        request = Request(environ)
        response = self.dispatch(request)
        return response(environ, start_response)

    def dispatch(self, request: Request) -> Response:
        ctx = _RequestCtx()
        incoming = tracing.parse_traceparent(
            request.headers.get(tracing.TRACEPARENT_HEADER)
        )
        adapter = self.url_map.bind_to_environ(request.environ)
        if request.path in self._TRACE_EXEMPT_PATHS:
            ctx.trace_id = incoming.trace_id if incoming is not None else ""
            with ctx.ledger.activate():
                return self._dispatch_traced(
                    ctx, request, adapter, tracing.NOOP_SPAN
                )
        with tracing.start_span(
            "router.request",
            parent=incoming,
            method=request.method,
            path=request.path,
        ) as span:
            ctx.trace_id = span.trace_id or (
                incoming.trace_id if incoming is not None else ""
            )
            # the ledger activation makes this request's phase brackets
            # (and record_current from replica calls on THIS thread)
            # visible to the router-plane histograms
            with ctx.ledger.activate():
                return self._dispatch_traced(ctx, request, adapter, span)

    def _dispatch_traced(self, ctx, request, adapter, span) -> Response:
        endpoint = None
        try:
            endpoint, url_args = adapter.match()
            resolution = self._resolve_revision(ctx, request)
            if resolution is not None:
                response = resolution  # 410: revision gone
            else:
                handler = getattr(self, f"view_{endpoint}")
                response = handler(ctx, request, **url_args)
        except ApiError as exc:
            response = _json_response(exc.payload, exc.status)
            retry_after = exc.payload.get("retry_after_s")
            if retry_after is not None:
                response.headers["Retry-After"] = str(retry_after)
        except HTTPException as exc:
            response = exc.get_response(request.environ)
        except Exception:
            logger.error(
                "Unhandled router error:\n%s", traceback.format_exc()
            )
            response = _json_response(
                {"error": "Something unexpected happened in the router"},
                500,
            )
        span.set_attribute("endpoint", endpoint or "unmatched")
        span.set_attribute("status_code", response.status_code)
        if response.status_code >= 500:
            span.set_status("error")
        return self._finalize(ctx, request, response, endpoint)

    def _resolve_revision(
        self, ctx: _RequestCtx, request: Request
    ) -> typing.Optional[Response]:
        """The server's revision semantics, against the same artifacts:
        the env pointer (symlink-resolved, so a lifecycle promotion rolls
        the router's casualty view too), ``?revision=`` validated by the
        shared name policy (catalog.resolve_sibling_revision)."""
        env_var = self.config["MODEL_COLLECTION_DIR_ENV_VAR"]
        pointer = os.environ.get(env_var)
        if not pointer:
            # a misconfigured process must answer with a diagnosis, not
            # a KeyError-shaped 500 on the first request (run-router now
            # refuses to start without it; this guards embedded apps)
            return _json_response(
                {
                    "error": f"{env_var} is not set on the router process"
                    " — start it via `gordo-tpu run-router"
                    " --collection-dir PATH` (or export the env var)"
                },
                503,
            )
        ctx.collection_dir = pointer
        if os.path.islink(pointer.rstrip(os.sep) or os.sep):
            ctx.collection_dir = os.path.realpath(pointer)
        ctx.current_revision = os.path.basename(ctx.collection_dir)
        requested = request.args.get("revision") or request.headers.get(
            "revision"
        )
        if requested:
            resolved = resolve_sibling_revision(ctx.collection_dir, requested)
            if resolved is None:
                return _json_response(
                    {"error": f"Revision '{requested}' not found."}, 410
                )
            ctx.revision = requested
            ctx.requested_revision = requested
            ctx.collection_dir = resolved
        else:
            ctx.revision = ctx.current_revision
        return None

    def _finalize(self, ctx, request, response, endpoint) -> Response:
        if ctx.revision:
            if response.mimetype == "application/json":
                # same body stamp as the server's responses, so clients
                # can't tell a router from a single replica
                with ctx.ledger.phase("serialize"):
                    try:
                        data = json.loads(response.get_data())
                        if isinstance(data, dict) and "revision" not in data:
                            data["revision"] = (
                                response.headers.get("revision")
                                or ctx.revision
                            )
                            response.set_data(json.dumps(data).encode())
                    except ValueError:
                        pass
            if "revision" not in response.headers:
                response.headers["revision"] = ctx.revision
        runtime_s = timeit.default_timer() - ctx.start_time
        ctx.ledger.finish(span=tracing.current_span(), wall_s=runtime_s)
        # append to any Server-Timing the proxied replica already
        # stamped, so its model_load/predict phases survive the hop
        entry = f"router_total;dur={runtime_s * 1000.0:.2f}"
        existing = response.headers.get("Server-Timing")
        response.headers["Server-Timing"] = (
            f"{existing}, {entry}" if existing else entry
        )
        if ctx.trace_id:
            response.headers[tracing.TRACE_ID_RESPONSE_HEADER] = ctx.trace_id
        return response

    # -- admission control -------------------------------------------------

    def _admit(self) -> None:
        if not self._inflight.acquire(blocking=False):
            get_registry().counter(
                "gordo_router_sheds_total",
                "Requests shed at the router's own admission door",
            ).inc()
            retry_after = round(max(0.1, 2.0 * self._ema_request_s), 2)
            raise ApiError(
                {
                    "error": "Router at max in-flight requests; retry later",
                    "max_inflight": self.max_inflight,
                    "retry_after_s": retry_after,
                },
                503,
            )

    def _release(self, started: float) -> None:
        self._inflight.release()
        elapsed = timeit.default_timer() - started
        with self._ema_lock:
            self._ema_request_s += 0.2 * (elapsed - self._ema_request_s)

    def _count_request(self, outcome: str) -> None:
        get_registry().counter(
            "gordo_router_requests_total",
            "Routed prediction requests by outcome "
            "(ok/partial/shed/refused/error)",
            ("outcome",),
        ).inc(outcome=outcome)

    # -- routing -----------------------------------------------------------

    def _candidates(
        self,
        name: str,
        ring: HashRing,
        replicas: typing.Dict[str, str],
    ) -> typing.Tuple[typing.List[str], str]:
        """(routable candidate replicas in ring preference order, true
        owner). Empty list = every candidate is ejected."""
        preference = [r for r in ring.preference(name) if r in replicas]
        owner = preference[0] if preference else ""
        return [r for r in preference if self.health.routable(r)], owner

    def _refuse_unavailable(self, ctx, names) -> None:
        """Build-report casualties 409 from the router EXACTLY as from a
        single server — same body shape, same reasons — before any
        replica is touched (docs/robustness.md)."""
        unavailable = self.catalog.unavailable_machines(ctx.collection_dir)
        bad = {n: unavailable[n] for n in names if n in unavailable}
        if bad:
            raise ApiError(
                {
                    "error": "Machine(s) unavailable in this revision: "
                    + ", ".join(
                        f"{name} ({info['reason']})"
                        for name, info in sorted(bad.items())
                    ),
                    "unavailable": bad,
                },
                409,
            )

    def _replica_call(
        self,
        rid: str,
        base_url: str,
        method: str,
        path: str,
        *,
        params=None,
        json_body=None,
        files=None,
        data=None,
        headers=None,
        span_name: str = "router.fanout",
        span_attrs: typing.Optional[dict] = None,
        parent_ctx=None,
    ) -> requests.Response:
        """One HTTP call to a replica under its span, through the chaos
        seam, with passive health recording. Raises on transport errors
        (recorded as failures); HTTP status handling is the caller's."""
        with tracing.start_span(
            span_name, parent=parent_ctx, replica=rid, **(span_attrs or {})
        ) as span:
            action = faults.replica_fault_action(rid)
            if action is not None:
                if action[0] == "die":
                    self.health.record_failure(rid)
                    span.set_status("error")
                    raise requests.ConnectionError(
                        f"injected replica death: {rid}"
                    )
                if action[0] == "slow":
                    time.sleep(action[1])
            send_headers = dict(headers or {})
            send_headers.update(tracing.propagation_headers(span))
            # downstream replica wait is the router's "queue" phase; on
            # fan-out/hedge worker threads there is no active ledger, so
            # this no-ops and the caller's pool-wait bracket accounts it
            t_wait = time.perf_counter()
            try:
                resp = self.session.request(
                    method,
                    f"{base_url}{path}",
                    params=params,
                    json=json_body,
                    files=files,
                    data=data,
                    headers=send_headers,
                    timeout=self.replica_timeout_s,
                )
            except Exception:
                self.health.record_failure(rid)
                span.set_status("error")
                raise
            finally:
                attribution.record_current(
                    "queue", time.perf_counter() - t_wait
                )
            if resp.status_code >= 500 and resp.status_code != 503:
                # 5xx (not a structured shed) counts against health too
                self.health.record_failure(rid)
            else:
                self.health.record_success(rid)
            span.set_attribute("status_code", resp.status_code)
            return resp

    # -- views: local (artifact-derived) -----------------------------------

    def view_healthcheck(self, ctx, request) -> Response:
        return Response("", 200)

    def view_server_version(self, ctx, request) -> Response:
        return _json_response({"version": __version__, "role": "router"})

    def view_replicas(self, ctx, request) -> Response:
        replicas, ring = self.routing_view()
        return _json_response(
            {
                "replicas": replicas,
                "vnodes": ring.vnodes,
                "health": self.health.snapshot(),
            }
        )

    def view_set_replicas(self, ctx, request) -> Response:
        body = request.get_json(silent=True) or {}
        replicas = body.get("replicas")
        if not isinstance(replicas, dict) or not replicas:
            return _json_response(
                {"error": "Body must carry a non-empty 'replicas' mapping "
                 "of id -> base URL."},
                400,
            )
        self.set_replicas({str(k): str(v) for k, v in replicas.items()})
        return self.view_replicas(ctx, request)

    def view_healthz(self, ctx, request) -> Response:
        """Router readiness: 503 + Retry-After while NO replica is
        routable (nothing can be served) — partial fleets stay ready,
        they just answer structured partials."""
        replicas, _ = self.routing_view()
        snapshot = self.health.snapshot()
        routable = [r for r in replicas if self.health.routable(r)]
        payload = {
            "status": "ok" if routable else "no_replicas",
            "replicas": snapshot,
            "routable": len(routable),
            "max_inflight": self.max_inflight,
        }
        if routable:
            return _json_response(payload)
        response = _json_response(payload, 503)
        retry_in = [
            s["retry_in_s"] for s in snapshot.values() if s["retry_in_s"] > 0
        ]
        response.headers["Retry-After"] = str(
            round(min(retry_in), 2) if retry_in else 1.0
        )
        return response

    def view_telemetry_snapshot(self, ctx, request) -> Response:
        """The snapshot contract: this ROUTER process's own registry
        dump + identity (the merged plane view lives at /status and
        /metrics — a rollup polling a router must not re-merge an
        already-merged registry)."""
        return _json_response(self._self_snapshot())

    def view_status(self, ctx, request) -> Response:
        """The plane /status: per-replica health/breaker state, shed
        rates, queue depths, stream backlogs, program-cache hit rate,
        last lifecycle tick — the one page `gordo-tpu top` renders."""
        return _json_response(rollup_mod.plane_status(self._merged_snapshot()))

    def view_metrics(self, ctx, request) -> Response:
        """Plane-level Prometheus exposition of the MERGED registries:
        counters are plane sums, gauges carry a `replica` label,
        histograms are bucket-wise merges."""
        merged = self._merged_snapshot()
        return Response(
            rollup_mod.render_prometheus_text(merged.get("metrics") or {}),
            mimetype="text/plain",
        )

    def view_models(self, ctx, request, gordo_project: str) -> Response:
        """The WHOLE collection's /models, derived from the shared
        artifacts — what a client sees through the router is the union
        of every replica's shard, regardless of which replicas are up."""
        available = self.catalog.list_machines(ctx.collection_dir)
        unavailable = self.catalog.unavailable_machines(ctx.collection_dir)
        payload: typing.Dict[str, typing.Any] = {
            "models": [m for m in available if m not in unavailable],
        }
        if unavailable:
            payload["unavailable"] = unavailable
        return _json_response(payload)

    def view_revisions(self, ctx, request, gordo_project: str) -> Response:
        parent = os.path.join(ctx.collection_dir, "..")
        try:
            available = [
                name
                for name in os.listdir(parent)
                if not name.startswith(".")
                and os.path.isdir(os.path.join(parent, name))
                and not os.path.islink(os.path.join(parent, name))
            ]
        except FileNotFoundError:
            available = [ctx.current_revision]
        return _json_response(
            {"latest": ctx.current_revision, "available-revisions": available}
        )

    def view_metadata(
        self, ctx, request, gordo_project: str, gordo_name: str
    ) -> Response:
        """Metadata straight from the shared artifacts — it's a host-side
        file read, so discovery keeps working for a shard whose every
        replica is down (predictions are what failover is for). Stays
        served for build casualties, the PR-4 discipline."""
        from gordo_tpu.server import utils as server_utils

        try:
            metadata = server_utils.load_metadata(
                ctx.collection_dir, gordo_name
            )
        except FileNotFoundError:
            return _json_response(
                {"error": f"Metadata for '{gordo_name}' not found"}, 404
            )
        env_var = self.config["MODEL_COLLECTION_DIR_ENV_VAR"]
        return _json_response(
            {
                "gordo-server-version": __version__,
                "metadata": metadata,
                "env": {env_var: os.environ.get(env_var)},
            }
        )

    # -- views: proxied ----------------------------------------------------

    def view_proxy_get(
        self, ctx, request, gordo_project: str, gordo_name: str
    ) -> Response:
        """Metadata/download-model: routed to the owner with failover.
        Metadata stays served for build casualties (PR-4 discipline), so
        no 409 pre-check here."""
        replicas, ring = self.routing_view()
        candidates, owner = self._candidates(gordo_name, ring, replicas)
        if not candidates:
            raise ApiError(
                {
                    "error": f"No replica available for machine "
                    f"'{gordo_name}' (owner {owner or 'unknown'} and all "
                    "successors ejected)",
                    "retry_after_s": self._shard_retry_after([owner]),
                },
                503,
            )
        rid = candidates[0]
        adopted = rid != owner
        if adopted:
            self._note_failover(owner, gordo_name, 1)
        try:
            resp = self._replica_call(
                rid,
                replicas[rid],
                "GET",
                request.path,
                params=ctx.forward_params(request),
                headers={ADOPT_HEADER: "failover"} if adopted else None,
                span_name="router.failover" if adopted else "router.fanout",
                span_attrs=(
                    {"from_replica": owner, "machine": gordo_name}
                    if adopted
                    else {"machine": gordo_name}
                ),
                parent_ctx=tracing.current_context(),
            )
        except Exception as exc:
            raise ApiError(
                {
                    "error": f"Replica {rid} failed for machine "
                    f"'{gordo_name}': {exc}",
                    "retry_after_s": self._shard_retry_after([rid]),
                },
                503,
            )
        return self._passthrough(resp)

    @staticmethod
    def _passthrough(resp: requests.Response) -> Response:
        """A replica response forwarded verbatim (body + the headers
        that matter; _finalize appends the router's own timing)."""
        out = Response(
            resp.content,
            status=resp.status_code,
            mimetype=(
                resp.headers.get("Content-Type", "application/json").split(";")[0]
            ),
        )
        for header in (
            "revision",
            "Retry-After",
            "Server-Timing",
            "Content-Disposition",
        ):
            value = resp.headers.get(header)
            if value:
                out.headers[header] = value
        return out

    def _note_failover(
        self, from_replica: str, to_target: str, n_machines: int
    ) -> None:
        get_registry().counter(
            "gordo_router_failovers_total",
            "Shard calls re-routed off their ring owner",
        ).inc()
        emit_event(
            "shard_failover",
            from_replica=from_replica,
            target=to_target,
            n_machines=n_machines,
        )

    def _shard_retry_after(self, replicas: typing.List[str]) -> float:
        """When the named replicas' ejection windows end — the honest
        Retry-After for their shard's casualties."""
        waits = [self.health.retry_after_s(r) for r in replicas if r]
        return round(max(waits), 2) if any(waits) else 1.0

    # -- views: single-machine prediction ----------------------------------

    def view_single_prediction(
        self, ctx, request, gordo_project: str, gordo_name: str
    ) -> Response:
        self._refuse_unavailable(ctx, [gordo_name])
        self._admit()
        started = timeit.default_timer()
        try:
            return self._single_prediction(ctx, request, gordo_name)
        finally:
            self._release(started)

    def _single_prediction(self, ctx, request, gordo_name: str) -> Response:
        replicas, ring = self.routing_view()
        candidates, owner = self._candidates(gordo_name, ring, replicas)
        if not candidates:
            self._count_request("partial")
            raise ApiError(
                self._transient_unavailable_payload(
                    {gordo_name: owner}, "every candidate replica is ejected"
                ),
                409,
            )
        rid = candidates[0]
        adopted = rid != owner
        if adopted:
            self._note_failover(owner, gordo_name, 1)
        headers = {}
        if request.content_type:
            headers["Content-Type"] = request.content_type
        if adopted:
            headers[ADOPT_HEADER] = "failover"
        try:
            resp = self._replica_call(
                rid,
                replicas[rid],
                "POST",
                request.path,
                params=ctx.forward_params(request),
                data=request.get_data(),
                headers=headers,
                span_name="router.failover" if adopted else "router.fanout",
                span_attrs=(
                    {"from_replica": owner, "machine": gordo_name}
                    if adopted
                    else {"machine": gordo_name}
                ),
                parent_ctx=tracing.current_context(),
            )
        except Exception as exc:
            # the failure feeds the breaker; the machine comes back as a
            # NAMED transient casualty, not an anonymous 500
            self._count_request("partial")
            raise ApiError(
                self._transient_unavailable_payload(
                    {gordo_name: owner},
                    f"routed replica {rid} failed ({exc})",
                ),
                409,
            )
        if resp.status_code == 421:
            # router/replica manifest drift (a membership change one
            # side hasn't seen yet): one adopt retry against the same
            # replica, exactly like the fleet path — drift must
            # self-heal, not hard-fail single predictions
            try:
                resp = self._replica_call(
                    rid,
                    replicas[rid],
                    "POST",
                    request.path,
                    params=ctx.forward_params(request),
                    data=request.get_data(),
                    headers={**headers, ADOPT_HEADER: "failover"},
                    span_name="router.fanout",
                    span_attrs={"machine": gordo_name, "adopt_retry": True},
                    parent_ctx=tracing.current_context(),
                )
            except Exception as exc:
                self._count_request("partial")
                raise ApiError(
                    self._transient_unavailable_payload(
                        {gordo_name: owner},
                        f"routed replica {rid} failed ({exc})",
                    ),
                    409,
                )
        # melting replica: propagate its structured 503 + Retry-After
        # untouched (docs/serving.md#dynamic-batching) — no failover,
        # the shed herd must not be sprayed onto its peers
        if resp.status_code < 400:
            self._count_request("ok")
        elif resp.status_code == 503:
            self._count_request("shed")
        else:
            self._count_request("refused")
        return self._passthrough(resp)

    def _transient_unavailable_payload(
        self, machines_to_owner: typing.Dict[str, str], why: str
    ) -> dict:
        unavailable = {
            name: {
                "reason": "replica_unavailable",
                "replica": owner,
                "retry_after_s": self._shard_retry_after([owner]),
            }
            for name, owner in machines_to_owner.items()
        }
        return {
            "error": "Machine(s) temporarily unroutable: "
            + ", ".join(sorted(machines_to_owner))
            + f" ({why})",
            "unavailable": unavailable,
            # the client maps a transient 409 to ReplicaUnavailable:
            # recorded per machine, NOT permanent for the revision
            "transient": True,
            "retry_after_s": max(
                info["retry_after_s"] for info in unavailable.values()
            ),
        }

    # -- views: fleet fan-out ----------------------------------------------

    def view_fleet_prediction(
        self, ctx, request, gordo_project: str
    ) -> Response:
        anomaly = "/anomaly/" in request.path
        machines = GordoApp._fleet_request_machines(request, anomaly=anomaly)
        if machines is None:
            return _json_response(
                {"error": "Body must contain a non-empty 'machines' mapping."},
                400,
            )
        names = tuple(sorted(machines))
        self._refuse_unavailable(ctx, names)
        self._admit()
        started = timeit.default_timer()
        try:
            return self._fleet_fanout(ctx, request, machines, anomaly)
        finally:
            self._release(started)

    def _fleet_fanout(
        self, ctx, request, machines: dict, anomaly: bool
    ) -> Response:
        replicas, ring = self.routing_view()
        # route every machine BEFORE any network call: machines with no
        # routable candidate 409 immediately (transient, named), so the
        # client re-POSTs the healthy remainder without any shard's work
        # being computed and thrown away. The routable set is computed
        # ONCE (one health-lock pass over N replicas) — the per-machine
        # work is a single ring bisect in the all-healthy common case,
        # with the full preference walk only for orphaned machines.
        routable = {r for r in replicas if self.health.routable(r)}
        shards: typing.Dict[str, typing.List[str]] = {}
        owners: typing.Dict[str, str] = {}
        dead: typing.Dict[str, str] = {}
        for name in sorted(machines):
            owner = ring.owner(name)
            owners[name] = owner
            if owner in routable:
                shards.setdefault(owner, []).append(name)
                continue
            successor = next(
                (r for r in ring.preference(name) if r in routable), None
            )
            if successor is None:
                dead[name] = owner
            else:
                shards.setdefault(successor, []).append(name)
        if dead:
            self._count_request("partial")
            raise ApiError(
                self._transient_unavailable_payload(
                    dead, "every candidate replica is ejected"
                ),
                409,
            )
        # routing off an ejected owner IS the failover — record it even
        # though no call to the dead owner is ever attempted, per TRUE
        # owner (one successor may absorb machines from several ejected
        # owners; each outage must show its own losses)
        for rid, group in sorted(shards.items()):
            moved_by_owner: typing.Dict[str, int] = {}
            for m in group:
                if owners[m] != rid:
                    moved_by_owner[owners[m]] = (
                        moved_by_owner.get(owners[m], 0) + 1
                    )
            for owner, n_moved in sorted(moved_by_owner.items()):
                self._note_failover(owner, rid, n_moved)

        parent_ctx = tracing.current_context()
        params = ctx.forward_params(request)
        ordered = sorted(shards.items())
        results: typing.List[_ShardResult] = []
        if len(ordered) == 1:
            rid, group = ordered[0]
            results.append(
                self._call_shard(
                    rid, group, owners, machines, anomaly, request, params,
                    replicas, ring, parent_ctx,
                )
            )
        elif ordered:
            # the whole fan-out wait is "queue" on the request thread
            # (the per-call record_current inside _replica_call no-ops
            # on the pool's worker threads)
            t_wait = time.perf_counter()
            with ThreadPoolExecutor(max_workers=len(ordered)) as pool:
                futures = [
                    pool.submit(
                        self._call_shard,
                        rid, group, owners, machines, anomaly, request,
                        params, replicas, ring, parent_ctx,
                    )
                    for rid, group in ordered
                ]
                results = [f.result() for f in futures]
            attribution.record_current(
                "queue", time.perf_counter() - t_wait
            )
        return self._join_fleet_results(ctx, ordered, owners, results)

    def _shard_body(
        self,
        group: typing.List[str],
        machines: dict,
        anomaly: bool,
        request: Request,
    ) -> typing.Tuple[typing.Optional[dict], typing.Optional[dict]]:
        """(json_body, files) for the sub-request carrying ``group``'s
        payloads — same JSON/multipart duality as the server surface."""
        if request.files:
            files: typing.Dict[str, bytes] = {}
            for name in group:
                raw = machines[name]
                if anomaly:
                    files[f"{name}.X"] = raw["X"]
                    files[f"{name}.y"] = raw["y"]
                else:
                    files[name] = raw
            return None, files
        return {"machines": {name: machines[name] for name in group}}, None

    def _call_shard(
        self,
        rid: str,
        group: typing.List[str],
        owners: typing.Dict[str, str],
        machines: dict,
        anomaly: bool,
        request: Request,
        params: dict,
        replicas: typing.Dict[str, str],
        ring: HashRing,
        parent_ctx,
    ) -> _ShardResult:
        """
        One shard's sub-request to its routed replica (with bounded
        hedging to the next routable successor for stragglers). A
        transport failure here is NOT retried elsewhere mid-request: it
        feeds the circuit breaker (driving ejection, after which routing
        re-partitions the shard pre-fanout) and the shard's machines
        come back as NAMED transient casualties — the structured partial
        the client's per-machine error channel absorbs. One failed
        request costs one named partial; it never cascades into
        doubled load on the survivors.
        """
        json_body, files = self._shard_body(group, machines, anomaly, request)
        # the adopt header tells a sharded replica these machines are
        # routed to it ON PURPOSE (failover off an ejected owner, or a
        # hedge): needed whenever any machine isn't ring-owned by the
        # callee
        failover_from = next(
            (owners[m] for m in group if owners[m] != rid), None
        )

        def attempt(replica: str, adopted: bool, hedge: bool = False):
            from_owner = failover_from if replica == rid else rid
            span_name = (
                "router.failover"
                if (adopted and not hedge)
                else "router.fanout"
            )
            attrs: typing.Dict[str, typing.Any] = {"n_machines": len(group)}
            if adopted and not hedge and from_owner:
                attrs["from_replica"] = from_owner
            if hedge:
                attrs["hedge"] = True
            resp = self._replica_call(
                replica,
                replicas[replica],
                "POST",
                request.path,
                params=params,
                json_body=json_body,
                files=files,
                headers={ADOPT_HEADER: "failover"} if adopted else None,
                span_name=span_name,
                span_attrs=attrs,
                parent_ctx=parent_ctx,
            )
            return self._classify_shard_response(replica, resp)

        adopted = failover_from is not None
        # the successor walk costs a ring scan + health-lock hits: only
        # pay it when hedging can actually use the candidate
        hedge_candidate = (
            next(
                (
                    r
                    for r in ring.preference(group[0])
                    if r in replicas and r != rid and self.health.routable(r)
                ),
                None,
            )
            if self.hedge_s > 0
            else None
        )
        try:
            if self.hedge_s > 0 and hedge_candidate is not None:
                result = self._hedged_attempt(
                    attempt, rid, hedge_candidate, adopted
                )
            else:
                result = attempt(rid, adopted)
        except Exception as exc:
            return _ShardResult("error", rid, payload=str(exc))
        if result.kind == "wrong_shard":
            # membership drift between router and replica manifest:
            # one adopt retry against the same replica
            try:
                result = attempt(rid, True)
            except Exception as exc:
                return _ShardResult("error", rid, payload=str(exc))
            if result.kind == "wrong_shard":
                return _ShardResult(
                    "error", rid, payload="replica refuses shard even "
                    "with adopt header (manifest drift)"
                )
        return result

    def _hedged_attempt(
        self, attempt, primary: str, backup: str, adopted: bool
    ) -> _ShardResult:
        """Bounded hedging: ONE extra copy of a straggling shard call to
        the next routable successor; first completion wins, the loser is
        discarded (predictions are idempotent). The pool is shut down
        without waiting — the straggler finishes in the background
        instead of holding the response hostage."""
        pool = ThreadPoolExecutor(max_workers=2)
        # both copies run on pool threads (no ledger sink there): the
        # wait below is this thread's "queue" phase — a no-op in turn
        # when _hedged_attempt itself runs on a fan-out worker
        t_wait = time.perf_counter()
        try:
            first = pool.submit(attempt, primary, adopted)
            try:
                return first.result(timeout=self.hedge_s)
            except FutureTimeout:
                pass
            get_registry().counter(
                "gordo_router_hedges_total",
                "Hedge requests fired for straggling shard calls",
            ).inc()
            second = pool.submit(attempt, backup, True, True)
            pending = {first, second}
            last_exc: typing.Optional[BaseException] = None
            last_result: typing.Optional[_ShardResult] = None
            while pending:
                done, pending = futures_wait(
                    pending, return_when=FIRST_COMPLETED
                )
                # both copies may land in ONE round: scan the whole done
                # set for a success before settling for a non-ok result
                for future in done:
                    exc = future.exception()
                    if exc is not None:
                        last_exc = exc
                        continue
                    result = future.result()
                    if result.kind == "ok":
                        return result
                    # non-ok (shed, refused): prefer waiting for the
                    # other copy — it may still succeed
                    last_result = result
            if last_result is not None:
                return last_result
            if last_exc is not None:
                raise last_exc
            raise RuntimeError("hedged attempt yielded no result")
        finally:
            attribution.record_current(
                "queue", time.perf_counter() - t_wait
            )
            pool.shutdown(wait=False)

    def _classify_shard_response(
        self, rid: str, resp: requests.Response
    ) -> _ShardResult:
        if 200 <= resp.status_code < 300:
            try:
                payload = resp.json()
            except ValueError:
                return _ShardResult(
                    "error", rid, payload="unparseable replica response"
                )
            return _ShardResult("ok", rid, payload=payload)
        if resp.status_code == 503:
            retry_after = resp.headers.get("Retry-After")
            try:
                retry_after_s = float(retry_after) if retry_after else 1.0
            except ValueError:
                retry_after_s = 1.0
            return _ShardResult(
                "overloaded", rid, retry_after=retry_after_s
            )
        if resp.status_code == 421:
            return _ShardResult("wrong_shard", rid)
        if resp.status_code == 409:
            try:
                detail = resp.json().get("unavailable") or {}
            except ValueError:
                detail = {}
            return _ShardResult("unavailable", rid, payload=detail)
        body: typing.Any
        try:
            body = resp.json()
        except ValueError:
            body = {"error": resp.text[:500]}
        return _ShardResult(
            "refused", rid, payload=body, status=resp.status_code
        )

    def _join_fleet_results(
        self,
        ctx,
        ordered: typing.List[typing.Tuple[str, typing.List[str]]],
        owners: typing.Dict[str, str],
        results: typing.List[_ShardResult],
    ) -> Response:
        """Re-join the shard outcomes into ONE response with the single-
        server contract: 200 merged data, or the most actionable
        structured error (503 shed > hard 4xx > named 409 casualties).
        ``ordered`` is the exact (replica, group) submission list the
        ``results`` were produced from — positional, so result-to-shard
        attribution cannot drift with scheduling changes."""
        overloaded = [r for r in results if r.kind == "overloaded"]
        if overloaded:
            # a melting shard: propagate the shed — the client's backoff
            # (jittered Retry-After) already knows what to do with it,
            # and answering partial data instead would hide the pressure
            self._count_request("shed")
            response = _json_response(
                {
                    "error": "Replica(s) shedding load: "
                    + ", ".join(sorted(r.replica for r in overloaded)),
                    "retry_after_s": max(r.retry_after for r in overloaded),
                },
                503,
            )
            response.headers["Retry-After"] = str(
                max(r.retry_after for r in overloaded)
            )
            return response
        refused = [r for r in results if r.kind == "refused"]
        if refused:
            # a deterministic 4xx (422 mixed group, bad input): repeatable,
            # so propagate the first — the client's fallback logic applies
            first = sorted(refused, key=lambda r: r.replica)[0]
            self._count_request("refused")
            return _json_response(first.payload, first.status)

        merged_data: typing.Dict[str, typing.Any] = {}
        casualties: typing.Dict[str, dict] = {}
        all_transient = True
        for result, (rid, group) in zip(results, ordered):
            if result.kind == "ok":
                merged_data.update(result.payload.get("data") or {})
            elif result.kind == "unavailable":
                # replica-level 409 (its build-report view named
                # casualties the router's didn't): preserve reasons
                for name, info in (result.payload or {}).items():
                    casualties[name] = info
                    all_transient = False
            else:  # error: the whole shard is a transient casualty
                for name in group:
                    casualties[name] = {
                        "reason": "replica_unavailable",
                        "replica": owners.get(name, rid),
                        "retry_after_s": self._shard_retry_after(
                            [owners.get(name, rid)]
                        ),
                    }
        if casualties:
            payload: typing.Dict[str, typing.Any] = {
                "error": "Machine(s) unavailable: "
                + ", ".join(sorted(casualties)),
                "unavailable": casualties,
            }
            if all_transient:
                payload["transient"] = True
                payload["retry_after_s"] = max(
                    info.get("retry_after_s", 1.0)
                    for info in casualties.values()
                )
            self._count_request("partial")
            raise ApiError(payload, 409)
        self._count_request("ok")
        return _json_response(
            {
                "data": merged_data,
                "time-seconds": (
                    f"{timeit.default_timer() - ctx.start_time:.4f}"
                ),
            }
        )


    # -- views: streaming (docs/serving.md "Streaming scoring") ------------

    def _stream_resume_error(
        self,
        reason: str,
        machines: typing.Sequence[str],
        replicas: typing.Sequence[str] = (),
    ) -> ApiError:
        """The structured resume 409 — same body shape as a replica's
        own, so the client publisher cannot tell the router from a
        single server: it reconnects (through the router) and replays,
        landing on whatever the CURRENT ring routes to."""
        return ApiError(
            {
                "error": f"Stream session gone ({reason})",
                "stream_resume": {
                    "reason": reason,
                    "machines": sorted(machines),
                },
                "transient": True,
                "retry_after_s": self._shard_retry_after(list(replicas)),
            },
            409,
        )

    def view_stream_open(
        self, ctx, request, gordo_project: str
    ) -> Response:
        # the SERVER's parser, shared verbatim (like
        # GordoApp._fleet_request_machines on the fleet path): the
        # router forwards the normalized form, so the wire contract
        # cannot drift between the two sides
        machines_spec = GordoApp._stream_machines_spec(
            request.get_json(silent=True) or {}
        )
        if machines_spec is None:
            return _json_response(
                {
                    "error": "Body must carry a non-empty 'machines' list "
                    "or mapping."
                },
                400,
            )
        names = sorted(machines_spec)
        self._refuse_unavailable(ctx, names)
        self._admit()
        started = timeit.default_timer()
        try:
            return self._stream_open(
                ctx, request, gordo_project, machines_spec, names
            )
        finally:
            self._release(started)

    def _stream_open(
        self, ctx, request, gordo_project, machines_spec, names
    ) -> Response:
        replicas, ring = self.routing_view()
        routable = {r for r in replicas if self.health.routable(r)}
        shards: typing.Dict[str, typing.List[str]] = {}
        owners: typing.Dict[str, str] = {}
        dead: typing.Dict[str, str] = {}
        for name in names:
            owner = ring.owner(name)
            owners[name] = owner
            target = (
                owner
                if owner in routable
                else next(
                    (r for r in ring.preference(name) if r in routable), None
                )
            )
            if target is None:
                dead[name] = owner
            else:
                shards.setdefault(target, []).append(name)
        if dead:
            self._count_request("partial")
            raise self._stream_resume_error(
                "every candidate replica is ejected", dead, dead.values()
            )
        parent_ctx = tracing.current_context()
        params = ctx.forward_params(request)
        subs: typing.List[dict] = []
        merged: typing.Dict[str, dict] = {}
        try:
            for rid, group in sorted(shards.items()):
                adopted = any(owners[m] != rid for m in group)
                for owner in sorted(
                    {owners[m] for m in group if owners[m] != rid}
                ):
                    self._note_failover(
                        owner, rid, sum(1 for m in group if owners[m] == owner)
                    )
                resp = self._replica_call(
                    rid,
                    replicas[rid],
                    "POST",
                    f"/gordo/v0/{gordo_project}/stream/open",
                    params=params,
                    json_body={
                        "machines": {m: machines_spec[m] for m in group}
                    },
                    headers={ADOPT_HEADER: "failover"} if adopted else None,
                    span_name="router.failover" if adopted else "router.fanout",
                    span_attrs={"n_machines": len(group), "stream": True},
                    parent_ctx=parent_ctx,
                )
                if resp.status_code == 503:
                    out = self._passthrough(resp)
                    self._count_request("shed")
                    self._close_subs(subs, gordo_project, params)
                    return out
                if resp.status_code in (400, 404, 410, 422) or (
                    resp.status_code == 409
                    and not (self._body_of(resp) or {}).get("transient")
                ):
                    # a deterministic refusal (bad spec, non-streamable
                    # or quarantined machine): repeatable, so it passes
                    # through VERBATIM — wrapping it as a transient
                    # resume would make the client retry a permanent
                    # condition and bury the real message
                    out = self._passthrough(resp)
                    self._count_request("refused")
                    self._close_subs(subs, gordo_project, params)
                    return out
                if resp.status_code >= 300:
                    raise IOError(
                        f"replica {rid} refused stream open "
                        f"({resp.status_code}): {resp.text[:300]}"
                    )
                payload = resp.json()
                subs.append(
                    {
                        "rid": rid,
                        "url": replicas[rid],
                        "sid": payload["session"],
                        "machines": list(group),
                    }
                )
                merged.update(payload.get("machines") or {})
        except Exception as exc:
            self._close_subs(subs, gordo_project, params)
            self._count_request("partial")
            raise self._stream_resume_error(
                f"stream open failed ({exc})", names, shards.keys()
            )
        proxy = _StreamProxy(
            uuid.uuid4().hex[:16], list(names), subs,
            project=gordo_project, params=params,
        )
        evicted: typing.List[_StreamProxy] = []
        with self._streams_lock:
            # opportunistic hygiene: purge abandoned proxies (a crashed
            # publisher never closes), and bound the table — an evicted
            # session costs its client one resume round-trip, never an
            # unbounded router footprint
            now = time.monotonic()
            for sid in [
                s
                for s, p in self._streams.items()
                if p.stale or now - p.last_active > STREAM_PROXY_IDLE_S
            ]:
                evicted.append(self._streams.pop(sid))
            while len(self._streams) >= STREAM_PROXY_BOUND:
                evicted.append(self._streams.pop(next(iter(self._streams))))
            self._streams[proxy.sid] = proxy
        for old in evicted:
            # free the replicas' device-resident windows now instead of
            # letting them idle to each replica's own eviction bound —
            # under the project/params the EVICTED proxy was opened with
            self._close_subs(
                old.subs, old.project or gordo_project, old.params
            )
        self._count_request("ok")
        return _json_response(
            {"session": proxy.sid, "machines": merged}, 201
        )

    def _close_subs(self, subs: typing.List[dict], project: str, params):
        """Best-effort close of downstream sub-sessions (their windows
        free now instead of idling to eviction)."""
        for sub in subs:
            try:
                self._replica_call(
                    sub["rid"],
                    sub["url"],
                    "POST",
                    f"/gordo/v0/{project}/stream/{sub['sid']}/close",
                    params=params,
                    span_attrs={"stream": True},
                )
            except Exception:  # noqa: BLE001 - cleanup only
                pass

    def view_stream_update(
        self, ctx, request, gordo_project: str, stream_id: str
    ) -> Response:
        with self._streams_lock:
            proxy = self._streams.get(stream_id)
            if proxy is not None and proxy.stale:
                self._streams.pop(stream_id, None)
        if proxy is None:
            raise self._stream_resume_error("unknown_session", [])
        if proxy.stale:
            self._close_subs(
                proxy.subs, proxy.project or gordo_project, proxy.params
            )
            raise self._stream_resume_error(
                "membership_changed", proxy.machines
            )
        proxy.last_active = time.monotonic()
        body = request.get_json(silent=True) or {}
        updates = body.get("updates")
        if not isinstance(updates, dict) or not updates:
            return _json_response(
                {"error": "Body must carry a non-empty 'updates' mapping."},
                400,
            )
        unknown = sorted(set(updates) - set(proxy.machines))
        if unknown:
            return _json_response(
                {"error": f"Machine(s) not in stream session: {unknown}"},
                400,
            )
        self._admit()
        started = timeit.default_timer()
        try:
            return self._stream_fanout(
                ctx, request, gordo_project, proxy, updates
            )
        finally:
            self._release(started)

    def _stream_fanout(
        self, ctx, request, gordo_project, proxy, updates
    ) -> Response:
        params = ctx.forward_params(request)
        parent_ctx = tracing.current_context()
        jobs = [
            (sub, {m: updates[m] for m in sub["machines"] if m in updates})
            for sub in proxy.subs
        ]
        jobs = [(sub, payload) for sub, payload in jobs if payload]

        def call(sub, payload):
            return self._replica_call(
                sub["rid"],
                sub["url"],
                "POST",
                f"/gordo/v0/{gordo_project}/stream/{sub['sid']}/update",
                params=params,
                json_body={"updates": payload},
                span_attrs={"n_machines": len(payload), "stream": True},
                parent_ctx=parent_ctx,
            )

        results: typing.List[typing.Tuple[dict, typing.Any]] = []
        try:
            if len(jobs) == 1:
                results = [(jobs[0][0], call(*jobs[0]))]
            elif jobs:
                t_wait = time.perf_counter()
                with ThreadPoolExecutor(max_workers=len(jobs)) as pool:
                    futures = [
                        (sub, pool.submit(call, sub, payload))
                        for sub, payload in jobs
                    ]
                    results = [(sub, f.result()) for sub, f in futures]
                attribution.record_current(
                    "queue", time.perf_counter() - t_wait
                )
        except Exception as exc:
            # a dead replica mid-stream: the breaker is already fed (it
            # drives ejection, so the client's re-open lands on the
            # successor); this session answers the resume contract
            proxy.stale = True
            self._count_request("partial")
            raise self._stream_resume_error(
                f"replica failed mid-stream ({exc})",
                proxy.machines,
                [sub["rid"] for sub, _ in jobs],
            )
        # classify ALL sub-outcomes before answering: a sub that
        # answered 200 already COMMITTED its machines' rows, so once
        # any sub succeeded the only safe non-200 answer is the resume
        # contract (the client's replayed tail re-anchors every
        # sub-session and the rows re-score) — passing a peer's 503
        # through would make the client retry the same seqs against the
        # committed sub, which trims them as overlap and their scores
        # would be lost for good
        scores: typing.Dict[str, dict] = {}
        ok = []
        shed = []
        refused = []
        lost = []
        for sub, resp in results:
            if 200 <= resp.status_code < 300:
                try:
                    scores.update(resp.json().get("scores") or {})
                    ok.append(sub)
                    continue
                except ValueError:
                    lost.append((sub, "unparseable response"))
            elif resp.status_code == 503:
                shed.append((sub, resp))
            elif resp.status_code in (400, 404, 422) or (
                resp.status_code == 409
                and "stream_resume" not in (self._body_of(resp) or {})
            ):
                # deterministic client-side 4xx (bad rows, quarantined
                # machine): repeatable, so surface it VERBATIM — a
                # resume/replay loop would re-send the same bad input
                # forever and bury the real message
                refused.append((sub, resp))
            else:
                # downstream resume 409 (replica evicted/rolled its own
                # session), 421 manifest drift, 5xx: session-loss shapes
                lost.append((sub, f"answered {resp.status_code}"))
        if refused:
            # another sub may have COMMITTED (ok) or broken (lost) while
            # this one refused: the 4xx still surfaces verbatim NOW, but
            # the proxy goes stale so the NEXT update answers the resume
            # contract and re-anchors every sub-session's seq — without
            # this, the committed sub is ahead of the client's cursor
            # and would trim the next update's fresh rows as overlap
            if ok or lost:
                proxy.stale = True
            self._count_request("refused")
            return self._passthrough(sorted(
                refused, key=lambda pair: pair[0]["rid"]
            )[0][1])
        if shed and not ok and not lost:
            # nothing committed anywhere: the shed propagates untouched
            # and the client's Retry-After retry is exact
            out = self._passthrough(shed[0][1])
            self._count_request("shed")
            return out
        if lost or shed:
            proxy.stale = True
            self._count_request("partial")
            raise self._stream_resume_error(
                "; ".join(
                    [f"replica {sub['rid']} {why}" for sub, why in lost]
                    + [f"replica {sub['rid']} shed mid-update" for sub, _ in shed]
                ),
                proxy.machines,
                [sub["rid"] for sub, _ in lost + shed],
            )
        self._count_request("ok")
        return _json_response({"session": proxy.sid, "scores": scores})

    @staticmethod
    def _body_of(resp) -> typing.Optional[dict]:
        try:
            body = resp.json()
        except ValueError:
            return None
        return body if isinstance(body, dict) else None

    def view_stream_close(
        self, ctx, request, gordo_project: str, stream_id: str
    ) -> Response:
        with self._streams_lock:
            proxy = self._streams.pop(stream_id, None)
        if proxy is not None:
            self._close_subs(
                proxy.subs, gordo_project, ctx.forward_params(request)
            )
        return _json_response(
            {"session": stream_id, "closed": proxy is not None}
        )


def parse_replica_entries(
    entries: typing.Iterable[str],
) -> typing.Dict[str, str]:
    """
    The ONE parser for ``id=url`` replica entries (each entry may itself
    be a comma-separated list — the env-var form). Shared by the CLI and
    the env fallback so both reject the same malformed input at startup
    instead of hashing machines onto an empty-string replica at request
    time.
    """
    replicas: typing.Dict[str, str] = {}
    flat: typing.List[str] = []
    for item in entries:
        flat.extend(p for p in str(item).split(",") if p.strip())
    for entry in flat:
        rid, sep, url = entry.strip().partition("=")
        rid, url = rid.strip(), url.strip().rstrip("/")
        if not sep or not rid or not url:
            raise ValueError(
                f"Replica entries must be id=url, got {entry!r}"
            )
        replicas[rid] = url
    return replicas


def build_router_app(config: typing.Optional[dict] = None) -> RouterApp:
    """Build the router WSGI app (env fallbacks mirror build_app)."""
    config = dict(config or {})
    if "REPLICAS" not in config and os.environ.get("GORDO_ROUTER_REPLICAS"):
        # "r0=http://h0:5555,r1=http://h1:5555"
        config["REPLICAS"] = parse_replica_entries(
            [os.environ["GORDO_ROUTER_REPLICAS"]]
        )
    for key, env, cast in (
        ("VNODES", "GORDO_ROUTER_VNODES", int),
        ("EJECT_AFTER", "GORDO_ROUTER_EJECT_AFTER", int),
        ("BACKOFF_SCALE", "GORDO_ROUTER_BACKOFF_SCALE", float),
        ("PROBE_INTERVAL_S", "GORDO_ROUTER_PROBE_INTERVAL_S", float),
        ("HEDGE_MS", "GORDO_ROUTER_HEDGE_MS", float),
        ("REPLICA_TIMEOUT_S", "GORDO_ROUTER_REPLICA_TIMEOUT_S", float),
        ("MAX_INFLIGHT", "GORDO_ROUTER_MAX_INFLIGHT", int),
        ("ROLLUP_INTERVAL_S", "GORDO_ROLLUP_INTERVAL_S", float),
        ("ROLLUP_RETENTION", "GORDO_ROLLUP_RETENTION", int),
        ("ROLLUP_PERSIST_PATH", "GORDO_ROLLUP_PERSIST", str),
    ):
        if key not in config and os.environ.get(env):
            config[key] = cast(os.environ[env])
    return RouterApp(config)


def run_router(
    host: str,
    port: int,
    log_level: str = "info",
    config: typing.Optional[dict] = None,
    threads: typing.Optional[int] = None,
):
    """Serve the router under the native runner (one process — the
    router holds no device, so scale-out is more routers behind a plain
    L4 balancer; see docs/serving.md)."""
    import logging as _logging

    from gordo_tpu.server.runner import ServerRunner

    _logging.getLogger("werkzeug").setLevel(log_level.upper())
    ServerRunner(
        app_factory=lambda: build_router_app(config),
        host=host,
        port=port,
        workers=1,
        threads=threads if threads is not None else 32,
    ).serve_forever()
