"""
The routing tier (docs/serving.md "Sharded serving plane"): one
collection's machines partitioned across N ``run-server`` replicas by a
consistent hash ring, with fleet requests fanned out to the owning
replicas and re-joined — and any ONE replica's death absorbed as a
routine event (ejection, failover to ring successors, re-adoption)
instead of an outage.

The router is pure host-side HTTP plumbing: it never touches JAX or the
models. It derives everything it knows — machine list, build-report
casualties — from the same artifact directory every replica already maps
in, so adding a replica is "start run-server with a shard manifest" and
adding a router is "point run-router at the same volume".
"""

from gordo_tpu.router.health import ReplicaHealthTracker
from gordo_tpu.router.ring import HashRing

__all__ = [
    "HashRing",
    "ReplicaHealthTracker",
    "RouterApp",
    "build_router_app",
]


def __getattr__(name):
    # router.app pulls in the server stack (it reuses the serving
    # catalog), and the serving catalog pulls in router.ring — importing
    # app eagerly here would close that loop into a cycle, so the two
    # WSGI-facing names load lazily
    if name in ("RouterApp", "build_router_app"):
        from gordo_tpu.router import app

        return getattr(app, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
