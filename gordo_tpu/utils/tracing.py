"""
Re-export shim — the jax-profiler trace hooks were promoted into the
observability subsystem (``gordo_tpu.observability.profiler``), next to
the distributed-tracing span layer whose dispatch spans bridge onto the
device timeline through them. Every historical import site (the builder,
tests, external users) keeps working unchanged; ``_active`` is the SAME
object as the package's, so test seams that flip it still steer the
real hooks.
"""

from gordo_tpu.observability.profiler import (  # noqa: F401  # lint: disable=unused-import
    PROFILE_DIR_ENV_VAR,
    _active,
    annotate,
    maybe_trace,
    profile_dir,
)

__all__ = ["PROFILE_DIR_ENV_VAR", "annotate", "maybe_trace", "profile_dir"]
