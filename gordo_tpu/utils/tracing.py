"""
Profiling/trace hooks — the TPU-native analogue of the reference's
lightweight timing surface (SURVEY.md §5: Server-Timing headers and
metadata-embedded durations, which this package also keeps).

``maybe_trace`` wraps a region in a ``jax.profiler`` trace when profiling
is enabled, producing TensorBoard-loadable dumps (XLA op timelines, HBM
usage) under ``<dir>/<name>-<timestamp>/``. Enable per-process with the
``GORDO_TPU_PROFILE_DIR`` env var or per-call with an explicit directory.

``annotate`` adds named spans inside an active trace so builder phases
(data fetch, CV folds, fit) are attributable on the timeline.
"""

import contextlib
import logging
import os
import threading
import time

logger = logging.getLogger(__name__)

PROFILE_DIR_ENV_VAR = "GORDO_TPU_PROFILE_DIR"

# set while a maybe_trace region is active, so annotate() works for both
# env-var and explicit-directory tracing
_active = threading.local()


def profile_dir() -> str:
    """Configured profile dump directory, or '' when profiling is off."""
    return os.environ.get(PROFILE_DIR_ENV_VAR, "")


@contextlib.contextmanager
def maybe_trace(name: str, directory: str = ""):
    """
    Trace the region into ``<directory>/<name>-<unix_ms>`` when a directory
    is configured (argument wins over env); no-op otherwise. Never lets a
    profiler failure break the traced workload.
    """
    directory = directory or profile_dir()
    if not directory:
        yield
        return

    target = os.path.join(directory, f"{name}-{int(time.time() * 1000)}")
    started = False
    try:
        import jax

        jax.profiler.start_trace(target)
        started = True
        _active.tracing = True
    except Exception:  # broken jax / profiler quirks / nested traces
        logger.warning("Could not start jax profiler trace", exc_info=True)
    try:
        yield
    finally:
        if started:
            _active.tracing = False
            try:
                import jax

                jax.profiler.stop_trace()
                logger.info("Wrote profiler trace to %s", target)
            except Exception:
                logger.warning("Could not stop jax profiler trace", exc_info=True)


@contextlib.contextmanager
def annotate(name: str):
    """
    Named span inside an active ``maybe_trace`` region. Cheap no-op when no
    trace is active, and never breaks the annotated workload if the
    profiler is unusable.
    """
    if not getattr(_active, "tracing", False):
        yield
        return
    try:
        import jax

        span = jax.profiler.TraceAnnotation(name)
    except Exception:  # broken jax
        yield
        return
    with span:
        yield
