"""
Small shared utilities (reference parity: gordo/util/__init__.py:1-3).
"""

from .utils import (
    capture_args,
    compile_cache_dir,
    compile_cache_dir_bytes,
    enable_compile_cache,
    honor_jax_platforms_env,
    replace_all_non_ascii_chars_with_default,
)
from . import atomic, disk_registry
from .compat import normalize_frequency

__all__ = [
    "capture_args",
    "compile_cache_dir",
    "compile_cache_dir_bytes",
    "enable_compile_cache",
    "honor_jax_platforms_env",
    "replace_all_non_ascii_chars_with_default",
    "atomic",
    "disk_registry",
    "normalize_frequency",
]
