"""
Reference parity: gordo/util/utils.py (capture_args) and
gordo/util/__init__.py (replace_all_non_ascii_chars).
"""

import functools
import inspect
import re


def capture_args(init):
    """
    Decorator for ``__init__`` that records the call's arguments on
    ``self._params`` so objects can round-trip through ``to_dict`` /
    ``from_dict`` (reference: gordo/util/utils.py:6-49).

    Positional args are resolved to their parameter names via the signature;
    defaults for parameters not passed are captured too, so the stored dict is
    the *effective* configuration.
    """

    @functools.wraps(init)
    def wrapper(self, *args, **kwargs):
        sig = inspect.signature(init)
        bound = sig.bind(self, *args, **kwargs)
        bound.apply_defaults()
        params = dict(bound.arguments)
        params.pop("self", None)
        # flatten a trailing **kwargs capture into the params dict itself
        for name, p in sig.parameters.items():
            if p.kind is inspect.Parameter.VAR_KEYWORD and name in params:
                params.update(params.pop(name))
            if p.kind is inspect.Parameter.VAR_POSITIONAL and name in params:
                params[name] = list(params[name])
        self._params = params
        return init(self, *args, **kwargs)

    return wrapper


def replace_all_non_ascii_chars_with_default(value: str, default: str = "-") -> str:
    """Replace every non-ASCII character in ``value`` with ``default``."""
    return re.sub(r"[^\x00-\x7F]", default, value)


def honor_jax_platforms_env() -> None:
    """
    Make ``JAX_PLATFORMS=cpu`` effective even where a TPU plugin pins
    ``jax_platforms`` via sitecustomize at interpreter start (which silently
    overrides the env var). Call before any JAX backend initializes; no-op
    when the env var is unset or JAX is absent.
    """
    import os

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        try:
            import jax
        except ImportError:
            return

        jax.config.update("jax_platforms", "cpu")
