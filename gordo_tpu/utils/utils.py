"""
Reference parity: gordo/util/utils.py (capture_args) and
gordo/util/__init__.py (replace_all_non_ascii_chars).
"""

import functools
import inspect
import logging
import re

logger = logging.getLogger(__name__)


def capture_args(init):
    """
    Decorator for ``__init__`` that records the call's arguments on
    ``self._params`` so objects can round-trip through ``to_dict`` /
    ``from_dict`` (reference: gordo/util/utils.py:6-49).

    Positional args are resolved to their parameter names via the signature;
    defaults for parameters not passed are captured too, so the stored dict is
    the *effective* configuration.
    """

    @functools.wraps(init)
    def wrapper(self, *args, **kwargs):
        sig = inspect.signature(init)
        bound = sig.bind(self, *args, **kwargs)
        bound.apply_defaults()
        params = dict(bound.arguments)
        params.pop("self", None)
        # flatten a trailing **kwargs capture into the params dict itself
        for name, p in sig.parameters.items():
            if p.kind is inspect.Parameter.VAR_KEYWORD and name in params:
                params.update(params.pop(name))
            if p.kind is inspect.Parameter.VAR_POSITIONAL and name in params:
                params[name] = list(params[name])
        self._params = params
        return init(self, *args, **kwargs)

    return wrapper


def replace_all_non_ascii_chars_with_default(value: str, default: str = "-") -> str:
    """Replace every non-ASCII character in ``value`` with ``default``."""
    return re.sub(r"[^\x00-\x7F]", default, value)


def honor_jax_platforms_env() -> None:
    """
    Make ``JAX_PLATFORMS=cpu`` effective even where a TPU plugin pins
    ``jax_platforms`` via sitecustomize at interpreter start (which silently
    overrides the env var). Call before any JAX backend initializes; no-op
    when the env var is unset or JAX is absent.
    """
    import os

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        try:
            import jax
        except ImportError:
            return

        jax.config.update("jax_platforms", "cpu")


def _host_cpu_fingerprint() -> str:
    """
    Short digest of this host's CPU ISA features, namespacing the default
    compile-cache dir per machine type. XLA:CPU persists AOT executables
    compiled for the build host's exact feature set; a workspace moved to
    a different CPU (fewer features — e.g. avx512/amx gone) would load
    those artifacts and fault or hang instead of recompiling.
    """
    import hashlib
    import platform

    material = platform.machine()
    try:
        # BOTH the model name and the feature flags: XLA derives target
        # features from the CPU model (e.g. prefer-no-scatter) that the
        # flags line alone does not capture, so two hosts with identical
        # flags but different silicon must still hash apart
        wanted = {"flags": False, "Features": False, "model name": False}
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                for prefix, seen in wanted.items():
                    if not seen and line.startswith(prefix):
                        material += line
                        wanted[prefix] = True
                if all(wanted.values()):
                    break
    except OSError:
        material += platform.processor() or ""
    return hashlib.sha1(material.encode()).hexdigest()[:12]


def enable_compile_cache(
    directory: "str | None" = None, min_compile_seconds: float = 0.5
) -> None:
    """
    Point JAX's persistent compilation cache at a disk directory so repeat
    processes skip re-compiling — including the many ~0.5s eager-op
    compiles a tunneled TPU backend pays per build (sub-second programs
    fall under JAX's default 1s persistence threshold and recompile every
    run without this).

    Directory resolution: explicit argument, else ``GORDO_XLA_CACHE_DIR``
    (set it to the empty string to disable), else a per-user temp-dir
    default that is created 0700 and must be OWNED by this uid — an
    attacker-pre-created directory in sticky /tmp would otherwise feed
    this process foreign compiled executables, so a foreign-owned default
    disables the cache instead. Failures (read-only filesystem, old jax)
    are logged and ignored — the cache is an optimization, never a
    requirement.
    """
    import os
    import tempfile

    if directory is None:
        directory = os.environ.get("GORDO_XLA_CACHE_DIR")
    if directory == "":
        return
    if directory is None:
        directory = os.path.join(
            tempfile.gettempdir(),
            f"gordo_tpu_xla_cache_{os.getuid()}_{_host_cpu_fingerprint()}",
        )
        try:
            import stat as stat_mod

            os.makedirs(directory, mode=0o700, exist_ok=True)
            # verify THROUGH an O_NOFOLLOW fd so the checked inode is the
            # used one: a plain lstat-then-chmod leaves a window in sticky
            # /tmp where the dir can be swapped for a symlink between the
            # check and the use (and chmod follows symlinks)
            fd = os.open(directory, os.O_RDONLY | os.O_DIRECTORY | os.O_NOFOLLOW)
            try:
                st = os.fstat(fd)
                if not stat_mod.S_ISDIR(st.st_mode) or st.st_uid != os.getuid():
                    logger.warning(
                        "Compile cache dir %s is owned by another user; "
                        "skipping the persistent cache", directory,
                    )
                    return
                # backstop for kernels that ignore O_NOFOLLOW on
                # directory symlinks (observed under gVisor/runsc, which
                # reports 4.4.0): a post-open lstat still rejects a
                # planted link, albeit without the atomicity the flag
                # provides on a conforming kernel
                if stat_mod.S_ISLNK(os.lstat(directory).st_mode):
                    logger.warning(
                        "Compile cache path %s is a symlink; "
                        "skipping the persistent cache", directory,
                    )
                    return
                # tighten a pre-existing dir created under a loose umask
                if st.st_mode & 0o077:
                    os.fchmod(fd, 0o700)
            finally:
                os.close(fd)
        except OSError as exc:
            logger.warning("Cannot prepare compile cache dir: %s", exc)
            return
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", directory)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", float(min_compile_seconds)
        )
    except Exception as exc:  # noqa: BLE001 - cache is best-effort
        logger.warning("Persistent XLA compile cache unavailable: %s", exc)
        return
    global _active_compile_cache_dir
    _active_compile_cache_dir = directory
    try:
        from gordo_tpu.observability import emit_event

        # the cache used to be configured silently; the event makes the
        # resolved directory (and thereby which runs shared it) visible
        # in telemetry reports (docs/observability.md)
        emit_event(
            "compile_cache_enabled",
            directory=directory,
            min_compile_seconds=float(min_compile_seconds),
        )
    except Exception:  # noqa: BLE001 - telemetry never gates the cache
        logger.debug("compile_cache_enabled event not emitted", exc_info=True)


#: the directory the last successful enable_compile_cache pointed JAX at
_active_compile_cache_dir: "str | None" = None


def compile_cache_dir() -> "str | None":
    """The active persistent compile-cache directory (None = never
    enabled in this process, or disabled)."""
    return _active_compile_cache_dir


def compile_cache_dir_bytes(directory: "str | None" = None) -> "int | None":
    """
    Total on-disk bytes under the persistent compile cache (the
    ``gordo_compile_cache_dir_bytes`` gauge the builder samples at build
    start/end), or None when no cache is enabled/readable — the
    CPU-test-friendly null, like the HBM watermark fields.
    """
    import os

    directory = directory if directory is not None else _active_compile_cache_dir
    if not directory:
        return None
    total = 0
    try:
        for root, _, files in os.walk(directory):
            for fname in files:
                try:
                    total += os.path.getsize(os.path.join(root, fname))
                except OSError:
                    continue
    except OSError:
        return None
    return total
