"""
Compatibility shims so reference-era configs run unchanged on modern pandas.

The reference uses pandas<2 frequency aliases ("10T", "8H", "1S") throughout
its configs and defaults (e.g. gordo/machine/dataset/datasets.py:84
``resolution="10T"``). pandas 3 removed the single-letter aliases for
minute/hour/second; this module maps legacy spellings onto their modern
equivalents so YAML configs written for the reference keep working.
"""

import re

# legacy single/upper-case alias -> modern lower-case alias
_LEGACY_ALIASES = {
    "T": "min",
    "MIN": "min",
    "H": "h",
    "S": "s",
    "L": "ms",
    "U": "us",
    "N": "ns",
}

_FREQ_RE = re.compile(r"^\s*(\d*\.?\d*)\s*([a-zA-Z]+)\s*$")


def normalize_frequency(freq: str) -> str:
    """
    Normalize a pandas frequency/offset alias: "10T" -> "10min", "8H" -> "8h".

    Strings that are not simple <number><alias> offsets (or use aliases we
    don't recognise) are returned unchanged so modern spellings pass through.

    Examples
    --------
    >>> normalize_frequency("10T")
    '10min'
    >>> normalize_frequency("8H")
    '8h'
    >>> normalize_frequency("1min")
    '1min'
    """
    if not isinstance(freq, str):
        return freq
    m = _FREQ_RE.match(freq)
    if not m:
        return freq
    num, alias = m.groups()
    if alias in ("ms", "us", "ns", "min", "h", "s"):  # already modern
        return freq
    replacement = _LEGACY_ALIASES.get(alias.upper())
    if replacement is None:
        return freq
    return f"{num}{replacement}"
