"""
A file-per-key registry on disk, used as the model build cache index.

Reference parity: gordo/util/disk_registry.py:17-117 — ``write_key`` /
``get_value`` / ``delete_value`` with keys as filenames under a registry dir.
"""

import logging
import os
import re
from pathlib import Path
from typing import Optional, Union

logger = logging.getLogger(__name__)

_VALID_KEY = re.compile(r"^(?!\.\.?\Z)[A-Za-z0-9_.\-]+\Z")


def _key_path(registry_dir: Union[os.PathLike, str], key: str) -> Path:
    if not _VALID_KEY.match(key):
        raise ValueError(
            f"Key {key!r} is not a valid registry key "
            "(allowed: letters, digits, '_', '.', '-')"
        )
    return Path(registry_dir) / key


def write_key(registry_dir: Union[os.PathLike, str], key: str, val: str):
    """
    Write ``val`` under ``key`` in the registry, creating the registry dir
    if needed. Overwrites any existing value (with a warning, like the
    reference).
    """
    path = _key_path(registry_dir, key)
    path.parent.mkdir(parents=True, exist_ok=True)
    if path.exists():
        logger.warning("Overwriting existing registry key %s", key)
    path.write_text(str(val))


def get_value(registry_dir: Union[os.PathLike, str], key: str) -> Optional[str]:
    """Read the value stored under ``key``; None if the key does not exist."""
    path = _key_path(registry_dir, key)
    if not path.is_file():
        return None
    return path.read_text()


def delete_value(registry_dir: Union[os.PathLike, str], key: str) -> bool:
    """Delete ``key`` from the registry. Returns True if something was deleted."""
    path = _key_path(registry_dir, key)
    if path.is_file():
        path.unlink()
        return True
    return False
