"""
Atomic filesystem publication, in one place.

Four subsystems grew their own copy of the write-temp-then-``os.replace``
discipline (the serializer's artifact flush, the builder's
``build_report.json``, the checkpoint manifest, the lifecycle ``latest``
pointer) and the lifecycle drift state made five. The shapes differ —
file, directory, symlink, create-exclusive — but the invariant is one:
a reader (the model server polling a report, a resuming build loading an
artifact, a peer worker scanning the ledger) must see the OLD complete
state or the NEW complete state, never a torn intermediate.

All helpers stage in the destination's own directory (``os.replace`` and
``os.link`` are only atomic within one filesystem) and clean their
staging entry up on failure, so a crash leaves at worst a dot/tmp file
the next run ignores.
"""

import json
import os
import shutil
import tempfile
import typing
from pathlib import Path


def atomic_write_bytes(
    path: typing.Union[str, os.PathLike], payload: bytes
) -> Path:
    """
    Publish raw bytes at ``path`` atomically (write-temp-then-replace;
    the binary sibling of :func:`atomic_write_json` — e.g. the program
    cache's serialized executables). Readers see the previous content
    or the new content, never a torn write. Parent directories are
    created as needed.
    """
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=f".{path.name}.tmp-")
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(payload)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def atomic_write_json(
    path: typing.Union[str, os.PathLike],
    payload: typing.Any,
    *,
    indent: typing.Optional[int] = None,
    sort_keys: bool = False,
    default: typing.Optional[typing.Callable] = None,
    trailing_newline: bool = True,
) -> Path:
    """
    Publish ``payload`` as JSON at ``path`` atomically: serialize into a
    sibling temp file, then ``os.replace`` it into place. Readers see
    the previous file or the new one, never a partial write. Parent
    directories are created as needed.
    """
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=f".{path.name}.tmp-")
    try:
        with os.fdopen(fd, "w") as fh:
            json.dump(
                payload, fh, indent=indent, sort_keys=sort_keys, default=default
            )
            if trailing_newline:
                fh.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def atomic_create_json(
    path: typing.Union[str, os.PathLike],
    payload: typing.Any,
    *,
    indent: typing.Optional[int] = None,
    sort_keys: bool = False,
    default: typing.Optional[typing.Callable] = None,
) -> Path:
    """
    Create-exclusive sibling of :func:`atomic_write_json`: publish the
    complete JSON file at ``path`` ONLY if nothing exists there, raising
    :class:`FileExistsError` otherwise — and never exposing a partial
    file to concurrent readers (the temp file is finished first, then
    ``os.link``-ed into place; the link either lands whole or fails).

    The first-writer-wins primitive the work ledger's done/casualty
    records are built on: N racing workers may each assemble a record,
    exactly one publication succeeds.
    """
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=f".{path.name}.tmp-")
    try:
        with os.fdopen(fd, "w") as fh:
            json.dump(
                payload, fh, indent=indent, sort_keys=sort_keys, default=default
            )
            fh.write("\n")
        os.link(tmp, path)  # atomic + exclusive: EEXIST if path exists
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass
    return path


def atomic_publish_dir(
    tmp_dir: typing.Union[str, os.PathLike],
    dest_dir: typing.Union[str, os.PathLike],
) -> Path:
    """
    Publish a fully-assembled staging DIRECTORY at ``dest_dir`` via one
    ``os.replace``. An existing destination is removed first —
    ``os.replace`` cannot rename onto a non-empty directory — which
    still cannot produce a torn result: the worst a crash between the
    two steps leaves is no directory at all, which readers (the resume
    scan, the ledger's rebuild-on-steal) treat as "not built".
    """
    tmp_dir, dest_dir = Path(tmp_dir), Path(dest_dir)
    if dest_dir.exists():
        shutil.rmtree(dest_dir)
    os.replace(tmp_dir, dest_dir)
    return dest_dir


def atomic_symlink_swap(
    target: typing.Union[str, os.PathLike],
    pointer: typing.Union[str, os.PathLike],
) -> None:
    """
    Re-point the symlink at ``pointer`` to ``target`` atomically: a
    fresh sibling symlink is created and ``os.replace``-d over the
    pointer, so readers resolve the old target or the new one, never a
    missing link. (``os.replace`` onto a symlink replaces the LINK, not
    what it points at.)
    """
    pointer = str(pointer)
    tmp = os.path.join(
        os.path.dirname(pointer) or ".",
        f".{os.path.basename(pointer)}-tmp-{os.getpid()}",
    )
    try:
        os.unlink(tmp)
    except OSError:
        pass
    os.symlink(str(target), tmp)
    try:
        os.replace(tmp, pointer)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
